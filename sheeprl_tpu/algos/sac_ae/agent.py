"""SAC-AE agent (flax): shared conv encoder + autoencoder + SAC heads
(reference: sheeprl/algos/sac_ae/agent.py:26-640; architecture from
https://arxiv.org/abs/1910.01741).

TPU restructuring:
- Pixels are NHWC end-to-end (the reference is NCHW).
- The Q ensemble is ONE module vmapped over a member axis, taking the
  ENCODED features (the encoder is a separate param tree so the critic
  update can propagate into it while the actor update cannot — the
  reference's `detach_encoder_features` flag becomes "which param trees the
  loss differentiates", which jax makes explicit for free).
- Target networks (critic ensemble AND encoder) are param copies EMA'd with
  their own taus by tree_map.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import prod
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.algos.sac.agent import squash_and_logprob
from sheeprl_tpu.models import MLP, CNN, DeCNN

LOG_STD_MIN = -10
LOG_STD_MAX = 2

orthogonal_init = jax.nn.initializers.orthogonal()


class SACAECNNEncoder(nn.Module):
    """4x conv k3 (strides 2,1,1,1) -> Dense -> LayerNorm -> tanh
    (reference: CNNEncoder, agent.py:26-87)."""

    keys: Sequence[str]
    features_dim: int
    channels_multiplier: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        x = CNN(
            hidden_channels=[32 * self.channels_multiplier] * 4,
            layer_args=[
                {"kernel_size": 3, "stride": 2},
                {"kernel_size": 3, "stride": 1},
                {"kernel_size": 3, "stride": 1},
                {"kernel_size": 3, "stride": 1},
            ],
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="model",
        )(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.Dense(self.features_dim, kernel_init=orthogonal_init, dtype=self.dtype, name="fc")(x)
        x = nn.LayerNorm(name="ln")(x)
        return jnp.tanh(x)


class SACAEMLPEncoder(nn.Module):
    """Vector branch (reference: MLPEncoder, agent.py:89-120)."""

    keys: Sequence[str]
    dense_units: int = 64
    mlp_layers: int = 2
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation="relu",
            norm_layer="layer_norm" if self.layer_norm else None,
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="model",
        )(x)


class SACAEEncoder(nn.Module):
    """Concat of the active branches' features."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    features_dim: int
    channels_multiplier: int
    dense_units: int
    mlp_layers: int
    layer_norm: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if len(self.cnn_keys) > 0:
            feats.append(
                SACAECNNEncoder(
                    keys=list(self.cnn_keys),
                    features_dim=self.features_dim,
                    channels_multiplier=self.channels_multiplier,
                    dtype=self.dtype,
                    name="cnn_encoder",
                )(obs)
            )
        if len(self.mlp_keys) > 0:
            feats.append(
                SACAEMLPEncoder(
                    keys=list(self.mlp_keys),
                    dense_units=self.dense_units,
                    mlp_layers=self.mlp_layers,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                    name="mlp_encoder",
                )(obs)
            )
        return jnp.concatenate(feats, axis=-1)


class SACAECNNDecoder(nn.Module):
    """Dense back to the conv grid -> 3x deconv s1 -> s2 deconv to pixels
    (reference: CNNDecoder, agent.py:153-202)."""

    keys: Sequence[str]
    channels: Sequence[int]  # per-key output channels
    conv_output_shape: Tuple[int, int, int]  # (H, W, C) at the encoder's conv output
    channels_multiplier: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> Dict[str, jax.Array]:
        batch = x.shape[:-1]
        x = nn.Dense(int(np.prod(self.conv_output_shape)), kernel_init=orthogonal_init, dtype=self.dtype, name="fc")(x)
        x = x.reshape(*batch, *self.conv_output_shape)
        x = DeCNN(
            hidden_channels=[32 * self.channels_multiplier] * 3,
            layer_args={"kernel_size": 3, "stride": 1},
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="model",
        )(x)
        x = DeCNN(
            hidden_channels=[sum(self.channels)],
            layer_args={"kernel_size": 3, "stride": 2, "output_padding": 1},
            activation=None,
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="to_obs",
        )(x)
        splits = np.cumsum(self.channels)[:-1]
        return dict(zip(self.keys, jnp.split(x, splits, axis=-1)))


class SACAEMLPDecoder(nn.Module):
    """MLP trunk + one head per vector key (reference: MLPDecoder, agent.py:122-151)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    dense_units: int = 64
    mlp_layers: int = 2
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation="relu",
            norm_layer="layer_norm" if self.layer_norm else None,
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="model",
        )(x)
        return {
            k: nn.Dense(d, kernel_init=orthogonal_init, dtype=self.dtype, name=f"head_{k}")(x)
            for k, d in zip(self.keys, self.output_dims)
        }


class SACAEDecoder(nn.Module):
    """Multi-branch decoder over the shared latent."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels: Sequence[int]
    mlp_output_dims: Sequence[int]
    conv_output_shape: Tuple[int, int, int]
    channels_multiplier: int
    dense_units: int
    mlp_layers: int
    layer_norm: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if len(self.cnn_keys) > 0:
            out.update(
                SACAECNNDecoder(
                    keys=list(self.cnn_keys),
                    channels=list(self.cnn_channels),
                    conv_output_shape=self.conv_output_shape,
                    channels_multiplier=self.channels_multiplier,
                    dtype=self.dtype,
                    name="cnn_decoder",
                )(latent)
            )
        if len(self.mlp_keys) > 0:
            out.update(
                SACAEMLPDecoder(
                    keys=list(self.mlp_keys),
                    output_dims=list(self.mlp_output_dims),
                    dense_units=self.dense_units,
                    mlp_layers=self.mlp_layers,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                    name="mlp_decoder",
                )(latent)
            )
        return out


class SACAEQFunction(nn.Module):
    """Q(features, act) MLP (reference: SACAEQFunction, agent.py:204-224)."""

    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([features, action], axis=-1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=1,
            activation="relu",
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="model",
        )(x)


class SACAEQEnsemble(nn.Module):
    n: int
    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: jax.Array, action: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            SACAEQFunction,
            in_axes=None,
            out_axes=-1,
            axis_size=self.n,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(hidden_size=self.hidden_size, dtype=self.dtype, name="qfs")
        return ensemble(features, action)[..., 0, :]


class SACAEActorModule(nn.Module):
    """Actor trunk over (detached) encoder features, tanh-bounded log_std
    (reference: SACAEContinuousActor, agent.py:240-318)."""

    action_dim: int
    hidden_size: int = 1024
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            kernel_init=orthogonal_init,
            dtype=self.dtype,
            name="model",
        )(features)
        mean = nn.Dense(self.action_dim, kernel_init=orthogonal_init, dtype=self.dtype, name="fc_mean")(x)
        log_std = nn.Dense(self.action_dim, kernel_init=orthogonal_init, dtype=self.dtype, name="fc_logstd")(x)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1)
        return mean, log_std


@dataclass(frozen=True)
class SACAEAgent:
    """Train state dict: {encoder, encoder_target, actor, qfs, qfs_target,
    decoder, log_alpha}."""

    encoder: SACAEEncoder
    decoder: SACAEDecoder
    actor: SACAEActorModule
    critics: SACAEQEnsemble
    action_scale: np.ndarray
    action_bias: np.ndarray
    target_entropy: float
    tau: float
    encoder_tau: float
    num_critics: int

    def encode(self, encoder_params, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.encoder.apply(encoder_params, obs)

    def decode(self, decoder_params, latent: jax.Array) -> Dict[str, jax.Array]:
        return self.decoder.apply(decoder_params, latent)

    def actions_and_log_probs(self, actor_params, features: jax.Array, key: jax.Array):
        mean, log_std = self.actor.apply(actor_params, features)
        # log_std is already tanh-bounded by the actor — no extra clip.
        return squash_and_logprob(
            mean, log_std, key, jnp.asarray(self.action_scale), jnp.asarray(self.action_bias),
            log_std_clip=None,
        )

    def q_values(self, qf_params, features: jax.Array, action: jax.Array) -> jax.Array:
        return self.critics.apply(qf_params, features, action)

    def next_target_q_values(
        self, state: Dict[str, Any], next_obs, rewards, terminated, gamma: float, key: jax.Array
    ) -> jax.Array:
        """Soft Bellman target through the TARGET encoder
        (reference: get_next_target_q_values, agent.py:402-409)."""
        next_features = self.encode(state["encoder"], next_obs)
        next_actions, next_log_pi = self.actions_and_log_probs(state["actor"], next_features, key)
        target_features = self.encode(state["encoder_target"], next_obs)
        qf_next = self.q_values(state["qfs_target"], target_features, next_actions)
        alpha = jnp.exp(state["log_alpha"])
        min_qf_next = jnp.min(qf_next, axis=-1, keepdims=True) - alpha * next_log_pi
        return rewards + (1 - terminated) * gamma * min_qf_next

    def get_actions(
        self, state: Dict[str, Any], obs: Dict[str, jax.Array], key: Optional[jax.Array] = None, greedy: bool = False
    ):
        features = self.encode(state["encoder"], obs)
        mean, log_std = self.actor.apply(state["actor"], features)
        scale = jnp.asarray(self.action_scale)
        bias = jnp.asarray(self.action_bias)
        if greedy:
            return jnp.tanh(mean) * scale + bias
        std = jnp.exp(log_std)
        x_t = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
        return jnp.tanh(x_t) * scale + bias


def build_agent(
    runtime,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACAEAgent, Dict[str, Any]]:
    """Construct modules + initial (or restored) train state
    (reference: build_agent, agent.py:500-640)."""
    act_dim = int(prod(action_space.shape))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    dtype = runtime.precision.compute_dtype
    screen = int(cfg.env.screen_size)

    encoder = SACAEEncoder(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        features_dim=int(cfg.algo.encoder.features_dim),
        channels_multiplier=int(cfg.algo.encoder.cnn_channels_multiplier),
        dense_units=int(cfg.algo.encoder.dense_units),
        mlp_layers=int(cfg.algo.encoder.mlp_layers),
        layer_norm=bool(cfg.algo.encoder.layer_norm),
        dtype=dtype,
    )
    # Spatial size after k3 strides (2,1,1,1) on screen x screen
    s = (screen - 3) // 2 + 1
    s = s - 2 * 3  # three stride-1 k3 convs
    conv_output_shape = (s, s, 32 * int(cfg.algo.decoder.cnn_channels_multiplier))
    decoder = SACAEDecoder(
        cnn_keys=cnn_dec_keys,
        mlp_keys=mlp_dec_keys,
        cnn_channels=[int(obs_space[k].shape[-1]) for k in cnn_dec_keys],
        mlp_output_dims=[int(np.prod(obs_space[k].shape)) for k in mlp_dec_keys],
        conv_output_shape=conv_output_shape,
        channels_multiplier=int(cfg.algo.decoder.cnn_channels_multiplier),
        dense_units=int(cfg.algo.decoder.dense_units),
        mlp_layers=int(cfg.algo.decoder.mlp_layers),
        layer_norm=bool(cfg.algo.decoder.layer_norm),
        dtype=dtype,
    )
    actor = SACAEActorModule(action_dim=act_dim, hidden_size=int(cfg.algo.hidden_size), dtype=dtype)
    critics = SACAEQEnsemble(n=int(cfg.algo.critic.n), hidden_size=int(cfg.algo.critic.hidden_size), dtype=dtype)

    agent = SACAEAgent(
        encoder=encoder,
        decoder=decoder,
        actor=actor,
        critics=critics,
        action_scale=np.asarray((action_space.high - action_space.low) / 2.0, np.float32),
        action_bias=np.asarray((action_space.high + action_space.low) / 2.0, np.float32),
        target_entropy=float(-act_dim),
        tau=float(cfg.algo.tau),
        encoder_tau=float(cfg.algo.encoder.tau),
        num_critics=int(cfg.algo.critic.n),
    )

    if agent_state is not None:
        state = jax.tree_util.tree_map(jnp.asarray, agent_state)
        return agent, state

    k_enc, k_dec, k_actor, k_qfs = jax.random.split(runtime.root_key, 4)
    dummy_obs = {
        k: jnp.zeros((1, *obs_space[k].shape), jnp.float32) for k in cnn_keys + mlp_keys
    }
    encoder_params = encoder.init(k_enc, dummy_obs)
    features = encoder.apply(encoder_params, dummy_obs)
    decoder_params = decoder.init(k_dec, features)
    actor_params = actor.init(k_actor, features)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)
    qf_params = critics.init(k_qfs, features, dummy_act)
    state = {
        "encoder": encoder_params,
        "encoder_target": jax.tree_util.tree_map(jnp.copy, encoder_params),
        "decoder": decoder_params,
        "actor": actor_params,
        "qfs": qf_params,
        "qfs_target": jax.tree_util.tree_map(jnp.copy, qf_params),
        "log_alpha": jnp.log(jnp.asarray([float(cfg.algo.alpha.alpha)], jnp.float32)),
    }
    return agent, state
