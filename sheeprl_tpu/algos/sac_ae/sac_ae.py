"""SAC-AE training loop (reference: sheeprl/algos/sac_ae/sac_ae.py:35-502).

SAC from pixels with a regularized autoencoder (https://arxiv.org/abs/1910.01741):

- critic update differentiates BOTH the shared encoder and the Q ensemble
  (one param group, one optimizer — the reference puts the encoder inside
  SACAECritic for the same effect);
- actor/alpha updates see stop-gradient encoder features (the reference's
  detach_encoder_features) on their own cadence;
- the decoder reconstructs bit-reduced, dequantized observations from the
  latent with an L2 latent penalty, updating encoder + decoder;
- target critic AND target encoder EMA with separate taus on the critic's
  cadence.

Update cadences are static python flags per gradient step, so each of the
four (actor x ema x decoder) combinations jit-specializes once.
"""

from __future__ import annotations

import copy
import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.sac import _make_optimizer
from sheeprl_tpu.algos.sac_ae.agent import SACAEAgent, build_agent
from sheeprl_tpu.algos.sac_ae.utils import normalize_pixels, prepare_obs, preprocess_obs, test
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.core.interact import InteractionPipeline
from sheeprl_tpu.core.mesh import DATA_AXIS
from sheeprl_tpu.core.player import PlayerPlacement
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.core.runtime import DispatchThrottle
from sheeprl_tpu.registry import register_algorithm
from sheeprl_tpu.utils.checkpoint import load_checkpoint, restore_opt_state, save_checkpoint
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


def make_train_step(agent: SACAEAgent, txs: Dict[str, optax.GradientTransformation], cfg: Dict[str, Any], mesh):
    """Build the jitted per-minibatch update, specialized on cadence flags."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    gamma = float(cfg.algo.gamma)
    l2_lambda = float(cfg.algo.decoder.l2_lambda)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    flat_sharding = NamedSharding(mesh, P(DATA_AXIS))

    @partial(jax.jit, donate_argnums=(0, 1), static_argnums=(4, 5, 6))
    def train_step(state, opt_states, batch, key, update_actor, update_ema, update_decoder):
        next_key, key = jax.random.split(key)
        batch = jax.lax.with_sharding_constraint(batch, {k: flat_sharding for k in batch})
        obs = normalize_pixels({k: batch[k] for k in cnn_keys + mlp_keys}, cnn_keys)
        next_obs = normalize_pixels(
            {k: batch[f"next_{k}"] for k in cnn_keys + mlp_keys}, cnn_keys
        )
        k_target, k_actor, k_rec = jax.random.split(key, 3)
        sg = jax.lax.stop_gradient

        # ------------------------- critic update (encoder + Q ensemble)
        next_target = agent.next_target_q_values(
            state, next_obs, batch["rewards"], batch["terminated"], gamma, k_target
        )

        def qf_loss_fn(params):
            features = agent.encode(params["encoder"], obs)
            qf_values = agent.q_values(params["qfs"], features, batch["actions"])
            return critic_loss(qf_values, next_target, agent.num_critics)

        qf_group = {"encoder": state["encoder"], "qfs": state["qfs"]}
        qf_l, qf_grads = jax.value_and_grad(qf_loss_fn)(qf_group)
        qf_updates, qf_opt = txs["qf"].update(qf_grads, opt_states["qf"], qf_group)
        qf_group = optax.apply_updates(qf_group, qf_updates)
        state["encoder"] = qf_group["encoder"]
        state["qfs"] = qf_group["qfs"]
        opt_states = dict(opt_states, qf=qf_opt)

        # --------------------------------------- target EMAs (own taus)
        if update_ema:
            state["qfs_target"] = jax.tree_util.tree_map(
                lambda p, tp: agent.tau * p + (1 - agent.tau) * tp,
                state["qfs"], state["qfs_target"],
            )
            state["encoder_target"] = jax.tree_util.tree_map(
                lambda p, tp: agent.encoder_tau * p + (1 - agent.encoder_tau) * tp,
                state["encoder"], state["encoder_target"],
            )

        metrics = {"value_loss": qf_l, "policy_loss": jnp.zeros(()), "alpha_loss": jnp.zeros(()),
                   "reconstruction_loss": jnp.zeros(())}

        # ------------------------- actor + alpha (frozen encoder features)
        if update_actor:
            features = sg(agent.encode(state["encoder"], obs))
            alpha = jnp.exp(state["log_alpha"])

            def actor_loss_fn(actor_params):
                actions, logprobs = agent.actions_and_log_probs(actor_params, features, k_actor)
                qf_values = agent.q_values(state["qfs"], features, actions)
                min_qf = jnp.min(qf_values, axis=-1, keepdims=True)
                return policy_loss(alpha, logprobs, min_qf), logprobs

            (actor_l, logprobs), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(state["actor"])
            actor_updates, actor_opt = txs["actor"].update(actor_grads, opt_states["actor"], state["actor"])
            state["actor"] = optax.apply_updates(state["actor"], actor_updates)

            def alpha_loss_fn(log_alpha):
                return entropy_loss(log_alpha, logprobs, agent.target_entropy)

            alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(state["log_alpha"])
            alpha_updates, alpha_opt = txs["alpha"].update(
                alpha_grads, opt_states["alpha"], state["log_alpha"]
            )
            state["log_alpha"] = optax.apply_updates(state["log_alpha"], alpha_updates)
            opt_states = dict(opt_states, actor=actor_opt, alpha=alpha_opt)
            metrics["policy_loss"] = actor_l
            metrics["alpha_loss"] = alpha_l

        # ----------------------------- autoencoder (encoder + decoder)
        if update_decoder:
            def rec_loss_fn(params):
                hidden = agent.encode(params["encoder"], obs)
                reconstruction = agent.decode(params["decoder"], hidden)
                loss = 0.0
                for k in cnn_dec_keys + mlp_dec_keys:
                    target = (
                        preprocess_obs(batch[k], k_rec, bits=5)
                        if k in cnn_dec_keys
                        else batch[k]
                    )
                    rec = reconstruction[k]
                    if k in mlp_dec_keys:
                        target = target.reshape(rec.shape)
                    loss += ((target - rec) ** 2).mean()
                # Latent L2 penalty applied ONCE (documented divergence: the
                # reference adds it inside the per-key loop, sac_ae.py:105-111,
                # scaling the regularizer with the number of decoder keys;
                # identical for the usual single-key configs).
                return loss + l2_lambda * 0.5 * (hidden**2).sum(-1).mean()

            rec_group = {"encoder": state["encoder"], "decoder": state["decoder"]}
            rec_l, rec_grads = jax.value_and_grad(rec_loss_fn)(rec_group)
            enc_updates, enc_opt = txs["encoder"].update(
                rec_grads["encoder"], opt_states["encoder"], state["encoder"]
            )
            state["encoder"] = optax.apply_updates(state["encoder"], enc_updates)
            dec_updates, dec_opt = txs["decoder"].update(
                rec_grads["decoder"], opt_states["decoder"], state["decoder"]
            )
            state["decoder"] = optax.apply_updates(state["decoder"], dec_updates)
            opt_states = dict(opt_states, encoder=enc_opt, decoder=dec_opt)
            metrics["reconstruction_loss"] = rec_l

        return state, opt_states, metrics, next_key

    return train_step


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    mesh = runtime.mesh
    rank = runtime.global_rank
    world_size = jax.process_count()

    if "minedojo" in str(cfg.env.wrapper.get("_target_", "")).lower():
        raise ValueError(
            "MineDojo is not currently supported by SAC-AE agent, since it does not take "
            "into consideration the action masks provided by the environment, but needed "
            "in order to play correctly the game. "
            "As an alternative you can use one of the Dreamers' agents."
        )

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference: sac_ae.py:137-138)
    cfg.env.screen_size = 64

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    health = runtime.health
    runtime.print(f"Log dir: {log_dir}")

    envs = make_vector_env(cfg, rank, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise RuntimeError(
            f"Unexpected action space, should be of type continuous (of type Box), got: {action_space}"
        )
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjoint")
    if len(set(cfg.algo.cnn_keys.decoder) - set(cfg.algo.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones, "
            f"got: decoder = {cfg.algo.cnn_keys.decoder}, encoder = {cfg.algo.cnn_keys.encoder}"
        )
    if len(set(cfg.algo.mlp_keys.decoder) - set(cfg.algo.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones, "
            f"got: decoder = {cfg.algo.mlp_keys.decoder}, encoder = {cfg.algo.mlp_keys.encoder}"
        )
    if cfg.metric.log_level > 0:
        runtime.print("Encoder CNN keys:", cfg.algo.cnn_keys.encoder)
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    # Eager flax/optax init runs host-side (each eager dispatch pays the
    # device-link round trip); the finished trees then move to the mesh.
    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime, cfg, observation_space, action_space,
            state_ckpt["agent"] if state_ckpt is not None else None,
        )

        txs = {
            "qf": _make_optimizer(cfg.algo.critic.optimizer),
            "actor": _make_optimizer(cfg.algo.actor.optimizer),
            "alpha": _make_optimizer(cfg.algo.alpha.optimizer),
            "encoder": _make_optimizer(cfg.algo.encoder.optimizer),
            "decoder": _make_optimizer(cfg.algo.decoder.optimizer),
        }
        opt_states = {
            "qf": txs["qf"].init({"encoder": agent_state["encoder"], "qfs": agent_state["qfs"]}),
            "actor": txs["actor"].init(agent_state["actor"]),
            "alpha": txs["alpha"].init(agent_state["log_alpha"]),
            "encoder": txs["encoder"].init(agent_state["encoder"]),
            "decoder": txs["decoder"].init(agent_state["decoder"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (
                ("qf", "qf_optimizer"),
                ("actor", "actor_optimizer"),
                ("alpha", "alpha_optimizer"),
                ("encoder", "encoder_optimizer"),
                ("decoder", "decoder_optimizer"),
            ):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])
    agent_state = runtime.shard_params(agent_state)
    opt_states = runtime.shard_params(opt_states)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(cfg.env.num_envs * world_size) if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        cfg.env.num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=obs_keys,
    )
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        rb = state_ckpt["rb"]

    last_train = 0
    train_step_count = 0
    start_iter = (state_ckpt["iter_num"] // world_size) + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * cfg.env.num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    def _player(s, o, k):
        # PRNG split + pixel normalization in-graph: ONE dispatch per step.
        next_k, sub = jax.random.split(k)
        return agent.get_actions(s, normalize_pixels(o, cnn_keys), sub, greedy=False), next_k

    player_fn = jax.jit(_player)
    train_fn = make_train_step(agent, txs, cfg, mesh)

    # Latency-aware player placement (core/player.py); off-policy: honors
    # fabric.player_sync=async. get_actions reads only encoder+actor, so
    # only that sub-tree is mirrored (critics/decoder never cross the link).
    def _player_view(state):
        return {"encoder": state["encoder"], "actor": state["actor"]}

    placement = PlayerPlacement.resolve(
        cfg, mesh.devices.flat[0], params=_player_view(agent_state)
    )
    placement.push(_player_view(agent_state))

    rollout_key, train_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key = placement.put(rollout_key)

    # Pipelined interaction (core/interact.py): per-slice policy dispatch +
    # async action fetch + double-buffered obs staging. slices=1/async off is
    # bit-identical to the serial loop. (No train overlap: sac_ae's train
    # step is not fused, so the dispatch itself is the host work.)
    pipeline = InteractionPipeline.from_config(cfg)
    pipeline.set_key(rollout_key)
    single_action_shape = envs.single_action_space.shape

    def _pipeline_policy(np_obs, state, key):
        with placement.ctx():
            actions_j, next_key = player_fn(placement.params(), np_obs, key)
        return actions_j, state, next_key

    def _prepare_slice(obs_slice, out=None):
        n = len(next(iter(obs_slice.values())))
        return prepare_obs(obs_slice, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=n, out=out)

    def _to_env_actions(host_actions, n_envs):
        return host_actions.reshape((n_envs, *single_action_shape))

    step_data = {}
    obs = pipeline.stash_obs(envs.reset(seed=cfg.seed)[0])

    cumulative_per_rank_gradient_steps = 0
    # Bound async in-flight train dispatches (core/runtime.py: an
    # unbounded queue pins every pending call's sampled batch on host).
    dispatch_throttle = DispatchThrottle()
    # Coalesced loss fetch + interval bounding (telemetry/step_timer.py):
    # ONE block_until_ready + ONE device_get per log interval.
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    keep_train_metrics = (aggregator is not None and not aggregator.disabled) or health.enabled
    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter
        telemetry.advance(policy_step)
        guard.advance(policy_step)

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
                next_obs, rewards, terminated, truncated, infos = envs.step(
                    actions.reshape(envs.action_space.shape)
                )
                next_obs = pipeline.stash_obs(next_obs)
            else:
                res = pipeline.interact(
                    envs,
                    obs,
                    _pipeline_policy,
                    prepare=_prepare_slice,
                    to_env_actions=_to_env_actions,
                )
                actions, next_obs, rewards, terminated, truncated, infos = (
                    res.outputs,
                    res.obs,
                    res.rewards,
                    res.terminated,
                    res.truncated,
                    res.infos,
                )
            rewards = rewards.reshape(cfg.env.num_envs, -1)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            for i in np.nonzero(fi.get("_episode", []))[0]:
                ep_rew = float(fi["episode"]["r"][i])
                ep_len = float(fi["episode"]["l"][i])
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        real_next_obs = copy.deepcopy(next_obs)
        if "final_obs" in infos:
            done_mask = np.logical_or(terminated, truncated)
            for idx in np.nonzero(done_mask)[0]:
                final = infos["final_obs"][idx]
                if final is not None:
                    for k, v in final.items():
                        real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = obs[k][np.newaxis]
            if not cfg.buffer.sample_next_obs:
                step_data[f"next_{k}"] = real_next_obs[k][np.newaxis]
        step_data["terminated"] = terminated.reshape(1, cfg.env.num_envs, -1).astype(np.float32)
        step_data["truncated"] = truncated.reshape(1, cfg.env.num_envs, -1).astype(np.float32)
        step_data["actions"] = actions.reshape(1, cfg.env.num_envs, -1).astype(np.float32)
        step_data["rewards"] = rewards[np.newaxis].astype(np.float32)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        obs = next_obs

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio((policy_step - prefill_steps + policy_steps_per_iter) / world_size)
            if per_rank_gradient_steps > 0:
                sample = rb.sample_tensors(
                    batch_size=per_rank_gradient_steps * cfg.algo.per_rank_batch_size,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )
                data = {
                    k: np.asarray(v).reshape(
                        per_rank_gradient_steps, cfg.algo.per_rank_batch_size, *np.asarray(v).shape[2:]
                    )
                    for k, v in sample.items()
                }
                data = {
                    k: v if k.removeprefix("next_") in cnn_keys else v.astype(np.float32)
                    for k, v in data.items()
                }
                with timer("Time/train_time"):
                    for i in range(per_rank_gradient_steps):
                        batch = {k: jnp.asarray(v[i]) for k, v in data.items()}
                        update_actor = (
                            cumulative_per_rank_gradient_steps % cfg.algo.actor.per_rank_update_freq == 0
                        )
                        update_ema = (
                            cumulative_per_rank_gradient_steps
                            % cfg.algo.critic.per_rank_target_network_update_freq
                            == 0
                        )
                        update_decoder = (
                            cumulative_per_rank_gradient_steps % cfg.algo.decoder.per_rank_update_freq == 0
                        )
                        with train_timer.step():
                            agent_state, opt_states, train_metrics, train_key = train_fn(
                                agent_state, opt_states, batch, train_key, update_actor, update_ema, update_decoder
                            )
                        # No sync here: the StepTimer queues the loss scalars
                        # (plus the which-updates-ran flags, which device_get
                        # passes through) and bounds the interval with ONE
                        # block at the log-interval flush.
                        train_timer.pend(
                            agent_state["actor"],
                            (train_metrics, update_actor, update_decoder)
                            if keep_train_metrics
                            else None,
                        )
                        dispatch_throttle.add(train_metrics)
                        cumulative_per_rank_gradient_steps += 1
                    placement.push(_player_view(agent_state))
                train_step_count += world_size

        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            # ONE bounding block + ONE device->host transfer for the whole
            # interval (StepTimer.flush) — the coalesced GL002 pattern. Only
            # losses whose update actually ran are fed to the aggregator —
            # the skipped branches report placeholder zeros.
            fetched_train_metrics = train_timer.flush()
            # Health sentinels inspect the same coalesced fetch — no extra
            # transfer; a nonfinite hit taints the run and escalates.
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            if aggregator and not aggregator.disabled:
                for m, did_actor, did_decoder in fetched_train_metrics:
                    aggregator.update("Loss/value_loss", m["value_loss"])
                    if did_actor:
                        aggregator.update("Loss/policy_loss", m["policy_loss"])
                        aggregator.update("Loss/alpha_loss", m["alpha_loss"])
                    if did_decoder:
                        aggregator.update("Loss/reconstruction_loss", m["reconstruction_loss"])
                # Collective when sync_on_compute is on: every rank joins;
                # only rank 0 (the only rank with a logger) writes.
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            logger.log(
                "Params/replay_ratio", cumulative_per_rank_gradient_steps * world_size / policy_step, policy_step
            )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log(
                        "Time/sps_env_interaction",
                        ((policy_step - last_log) / world_size * cfg.env.action_repeat)
                        / timer_metrics["Time/env_interaction_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": agent_state,
                "qf_optimizer": opt_states["qf"],
                "actor_optimizer": opt_states["actor"],
                "alpha_optimizer": opt_states["alpha"],
                "encoder_optimizer": opt_states["encoder"],
                "decoder_optimizer": opt_states["decoder"],
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            saved_tail = None
            tail = (rb._pos - 1) % rb.buffer_size
            if cfg.buffer.checkpoint:
                if rb["truncated"] is not None:
                    saved_tail = np.asarray(rb["truncated"][tail, :]).copy()
                    rb["truncated"][tail, :] = 1
                ckpt_state["rb"] = rb
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)
            if saved_tail is not None:
                rb["truncated"][tail, :] = saved_tail

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break
    pipeline.publish()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, agent_state, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
