"""SAC-AE auxiliary contract (reference: sheeprl/algos/sac_ae/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.env import make_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}
# The whole train state (incl. encoder/decoder params) checkpoints under
# one "agent" key, so that is the registered-model unit.
MODELS_TO_REGISTER = {"agent"}


def preprocess_obs(obs: jax.Array, key: jax.Array, bits: int = 8) -> jax.Array:
    """Bit-reduction + uniform dequantization noise for reconstruction
    targets (reference: utils.py:68-76; https://arxiv.org/abs/1807.03039)."""
    bins = 2**bits
    if bits < 8:
        obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    obs = obs + jax.random.uniform(key, obs.shape, obs.dtype) / bins
    return obs - 0.5


def prepare_obs(
    obs: Dict[str, np.ndarray],
    *,
    cnn_keys: Sequence[str] = (),
    mlp_keys: Sequence[str] = (),
    num_envs: int = 1,
    out: Dict[str, np.ndarray] = None,
) -> Dict[str, jax.Array]:
    """Host obs dict -> device dict; pixels stay uint8 (normalized in-graph).
    ``out`` is a previous result reused as a preallocated staging dict
    (core/interact.py ObsStager): float32 casts land in place; uint8 pixel
    entries are zero-copy views either way."""
    if out is not None:
        for k in cnn_keys:
            arr = np.asarray(obs[k])
            out[k] = arr.reshape(num_envs, *arr.shape[-3:])
        for k in mlp_keys:
            np.copyto(out[k], np.asarray(obs[k]).reshape(num_envs, -1))
        return out
    out = {}
    for k in cnn_keys:
        arr = np.asarray(obs[k])
        out[k] = arr.reshape(num_envs, *arr.shape[-3:])
    for k in mlp_keys:
        out[k] = np.asarray(obs[k], np.float32).reshape(num_envs, -1)
    return out


def normalize_pixels(obs: Dict[str, jax.Array], cnn_keys: Sequence[str]) -> Dict[str, jax.Array]:
    return {k: (v / 255.0 if k in cnn_keys else v) for k, v in obs.items()}


def test(agent, state, runtime, cfg: Dict[str, Any], log_dir: str, logger=None) -> float:
    """One greedy episode (reference: utils.py:28-53)."""
    env = make_env(cfg, None, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    get_actions = jax.jit(
        lambda s, o: agent.get_actions(s, normalize_pixels(o, cnn_keys), greedy=True)
    )
    while not done:
        jnp_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys)
        actions = get_actions(state, jnp_obs)
        obs, reward, done, truncated, _ = env.step(
            np.asarray(actions).reshape(env.action_space.shape)
        )
        done = done or truncated
        cumulative_rew += reward
        if cfg.dry_run:
            done = True
    runtime.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and logger is not None:
        logger.log_dict({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
    return cumulative_rew
