"""Plan2Explore on DreamerV2: agent construction
(reference: sheeprl/algos/p2e_dv2/agent.py:33-209).

Task side is the DV2 agent unchanged; P2E adds an exploration actor, an
exploration critic with its own target network, and the vmapped disagreement
ensemble (members predict the next stochastic state from latent + action).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import DV2Agent, build_agent as dv2_build_agent
from sheeprl_tpu.algos.dreamer_v3.agent import trunc_normal_init
from sheeprl_tpu.models import MLP


@dataclass(frozen=True)
class P2EDV2Agent:
    dv2: DV2Agent
    ensemble: MLP
    n_ensembles: int

    @property
    def actor(self):
        return self.dv2.actor

    @property
    def world_model(self):
        return self.dv2.world_model

    @property
    def actor_spec(self):
        return self.dv2.actor_spec

    @property
    def actions_dim(self):
        return self.dv2.actions_dim

    def ensemble_apply(self, stacked_params, x: jax.Array) -> jax.Array:
        return jax.vmap(lambda p: self.ensemble.apply(p, x))(stacked_params)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Any] = None,
    critic_task_state: Optional[Any] = None,
    target_critic_task_state: Optional[Any] = None,
    actor_exploration_state: Optional[Any] = None,
    critic_exploration_state: Optional[Any] = None,
    target_critic_exploration_state: Optional[Any] = None,
) -> Tuple[P2EDV2Agent, Dict[str, Any]]:
    dv2_agent, dv2_state = dv2_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
        target_critic_task_state,
    )
    wm_cfg = cfg.algo.world_model
    stoch_state_size = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    latent_state_size = stoch_state_size + int(wm_cfg.recurrent_model.recurrent_state_size)
    dtype = runtime.precision.compute_dtype

    ens_cfg = cfg.algo.ensembles
    use_ln = bool(ens_cfg.get("layer_norm", False))
    ensemble = MLP(
        hidden_sizes=[int(ens_cfg.dense_units)] * int(ens_cfg.mlp_layers),
        output_dim=stoch_state_size,
        activation="elu",
        norm_layer="layer_norm" if use_ln else None,
        norm_args={"eps": 1e-3} if use_ln else {},
        kernel_init=trunc_normal_init,
        dtype=dtype,
    )
    agent = P2EDV2Agent(dv2=dv2_agent, ensemble=ensemble, n_ensembles=int(ens_cfg.n))

    k_actor_expl, k_critic_expl, k_ens = jax.random.split(jax.random.fold_in(runtime.root_key, 2), 3)
    dummy_latent = jnp.zeros((1, latent_state_size), jnp.float32)

    if actor_exploration_state is not None:
        actor_expl_params = jax.tree_util.tree_map(jnp.asarray, actor_exploration_state)
    else:
        actor_expl_params = dv2_agent.actor.init(k_actor_expl, dummy_latent)

    if critic_exploration_state is not None:
        critic_expl_params = jax.tree_util.tree_map(jnp.asarray, critic_exploration_state)
    else:
        critic_expl_params = dv2_agent.critic.init(k_critic_expl, dummy_latent)
    if target_critic_exploration_state is not None:
        target_critic_expl_params = jax.tree_util.tree_map(jnp.asarray, target_critic_exploration_state)
    else:
        target_critic_expl_params = jax.tree_util.tree_map(jnp.copy, critic_expl_params)

    ens_in = int(np.sum(actions_dim)) + latent_state_size
    if ensembles_state is not None:
        ens_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)
    else:
        dummy_ens = jnp.zeros((1, ens_in), jnp.float32)
        ens_params = jax.vmap(lambda k: ensemble.init(k, dummy_ens))(
            jax.random.split(k_ens, int(ens_cfg.n))
        )

    state = {
        "world_model": dv2_state["world_model"],
        "actor_task": dv2_state["actor"],
        "critic_task": dv2_state["critic"],
        "target_critic_task": dv2_state["target_critic"],
        "actor_exploration": actor_expl_params,
        "critic_exploration": critic_expl_params,
        "target_critic_exploration": target_critic_expl_params,
        "ensembles": ens_params,
    }
    return agent, state
