"""A2C, coupled training loop (reference: sheeprl/algos/a2c/a2c.py:26-440).

Same rollout/GAE structure as PPO (the reference reuses the PPO agent,
a2c.py:14), but the update is a single pass with gradients ACCUMULATED over
minibatches and one optimizer step (reference: no_backward_sync accumulation,
a2c.py:64-112). Here that is a `lax.scan` over minibatches summing gradients,
followed by one `tx.update` — all inside one jitted call.
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.a2c.utils import prepare_obs, test
from sheeprl_tpu.algos.ppo.agent import PPOAgent, actions_metadata, build_agent
from sheeprl_tpu.algos.ppo.loss import entropy_loss
from sheeprl_tpu.config.instantiate import instantiate, locate
from sheeprl_tpu.core.interact import InteractionPipeline
from sheeprl_tpu.core.mesh import DATA_AXIS
from sheeprl_tpu.core.player import PlayerPlacement
from sheeprl_tpu.core.rollout import fuse_gae_pool, ship_rollout
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.registry import register_algorithm
from sheeprl_tpu.utils.checkpoint import load_checkpoint, restore_opt_state, save_checkpoint
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.ops import normalize_tensor
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


def make_train_step(agent: PPOAgent, tx: optax.GradientTransformation, cfg: Dict[str, Any], mesh):
    """One jitted update for the WHOLE iteration: bootstrap values for the
    last observation, GAE over the rollout, then a scan over minibatches
    accumulating grads into a single optimizer step.

    Fusing the bootstrap+GAE into the update (instead of separate
    `get_values`/`gae` dispatches whose returns/advantages round-tripped
    through the host) matters precisely on this algorithm: at the benchmark
    shape (5-step rollouts) A2C runs one update per 5 env steps, so
    per-iteration dispatch overhead is 1/25th of PPO's amortization — the
    audit VERDICT r4 weak #2 asked for. One dispatch, zero host fetches on
    the update path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mb_size = int(cfg.algo.per_rank_batch_size)
    obs_keys = list(cfg.algo.mlp_keys.encoder)
    normalize_advantages = bool(cfg.algo.get("normalize_advantages", False))
    reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    ent_coef = float(cfg.algo.get("ent_coef", 0.0))
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)

    def loss_fn(params, batch):
        obs = {k: batch[k] for k in obs_keys}
        logprobs, entropy, new_values = agent.evaluate_actions(params, obs, batch["actions"])
        advantages = batch["advantages"]
        if normalize_advantages:
            advantages = normalize_tensor(advantages)
        pg_loss = policy_loss(logprobs, advantages, reduction)
        v_loss = value_loss(new_values, batch["returns"], reduction)
        ent_loss = entropy_loss(entropy, reduction)
        total = pg_loss + vf_coef * v_loss + ent_coef * ent_loss
        return total, (pg_loss, v_loss)

    batch_sharding = NamedSharding(mesh, P(DATA_AXIS))

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, data, next_obs, key):
        # data is (T, E, ...) env-sharded (core/rollout.py); bootstrap +
        # GAE + flattening happen in-graph via the shared prologue.
        pool = fuse_gae_pool(
            agent, params, data, next_obs, (*obs_keys, "actions"),
            gamma, gae_lambda,
        )
        n = pool["actions"].shape[0]
        next_key, key = jax.random.split(key)
        num_mb = max(1, -(-n // mb_size))
        perm = jax.random.permutation(key, n)
        idx = perm[jnp.arange(num_mb * mb_size) % n].reshape(num_mb, mb_size)
        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)

        def mb_body(grads_acc, mb_idx):
            batch = {k: jnp.take(v, mb_idx, axis=0) for k, v in pool.items()}
            batch = jax.lax.with_sharding_constraint(batch, {k: batch_sharding for k in batch})
            (_, (pg, vl)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            return jax.tree_util.tree_map(jnp.add, grads_acc, grads), jnp.stack([pg, vl])

        grads_sum, metrics = jax.lax.scan(mb_body, zero_grads, idx)
        updates, opt_state = tx.update(grads_sum, opt_state, params)
        params = optax.apply_updates(params, updates)
        m = metrics.mean(0)
        return params, opt_state, {"policy_loss": m[0], "value_loss": m[1]}, next_key

    return train_step


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    mesh = runtime.mesh
    rank = runtime.global_rank

    state = None
    if cfg.checkpoint.resume_from:
        state = load_checkpoint(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    health = runtime.health
    runtime.print(f"Log dir: {log_dir}")

    envs = make_vector_env(cfg, rank, log_dir)
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the A2C agent: `algo.mlp_keys.encoder=[state]`")
    if cfg.metric.log_level > 0:
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    obs_keys = list(cfg.algo.mlp_keys.encoder)

    actions_dim, is_continuous = actions_metadata(envs.single_action_space)

    # Eager flax/optax init runs host-side (each eager dispatch pays the
    # device-link round trip); the finished trees then move to the mesh.
    with runtime.host_init():
        agent, params = build_agent(
            runtime, actions_dim, is_continuous, cfg, observation_space,
            state["agent"] if state is not None else None,
        )

        optim_cfg = dict(cfg.algo.optimizer)
        optim_target = optim_cfg.pop("_target_")
        base_lr = float(optim_cfg.pop("lr"))

        def make_tx(lr):
            inner = locate(optim_target)(lr=lr, **optim_cfg)
            if cfg.algo.max_grad_norm > 0.0:
                return optax.chain(optax.clip_by_global_norm(cfg.algo.max_grad_norm), inner)
            return inner

        tx = optax.inject_hyperparams(make_tx)(lr=base_lr)
        opt_state = tx.init(params)
        if state is not None:
            opt_state = restore_opt_state(opt_state, state["optimizer"])
    params = runtime.shard_params(params)
    opt_state = runtime.shard_params(opt_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    if cfg.buffer.size < cfg.algo.rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({cfg.algo.rollout_steps})"
        )
    rb = ReplayBuffer(
        cfg.buffer.size,
        cfg.env.num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=obs_keys,
    )

    world_size = jax.process_count()
    last_train = 0
    train_step_count = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs * cfg.algo.rollout_steps * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    player_step_fn = jax.jit(agent.player_step)
    # get_values_fn survives only for the (rare) mid-rollout truncation
    # bootstrap; end-of-rollout bootstrap + GAE live inside train_fn.
    get_values_fn = jax.jit(agent.get_values)
    train_fn = make_train_step(agent, tx, cfg, mesh)

    # Latency-aware player placement (core/player.py); on-policy => fresh.
    placement = PlayerPlacement.resolve(
        cfg, mesh.devices.flat[0], params=params, force_fresh=True
    )
    placement.push(params)

    rollout_key, train_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key = placement.put(rollout_key)

    # Async-capable action fetch (core/interact.py): with fabric.async_fetch
    # the D2H copy is submitted at dispatch time and harvested right before
    # envs.step; off it is op-for-op the old blocking fetch.
    pipeline = InteractionPipeline.from_config(cfg)

    # Coalesced loss fetch + interval bounding (telemetry/step_timer.py):
    # ONE block_until_ready + ONE device_get per log interval.
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    keep_train_metrics = (aggregator is not None and not aggregator.disabled) or health.enabled
    step_data = {}
    next_obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = next_obs[k][np.newaxis]

    for iter_num in range(start_iter, total_iters + 1):
        telemetry.advance(policy_step)
        guard.advance(policy_step)
        for _ in range(0, cfg.algo.rollout_steps):
            policy_step += cfg.env.num_envs * world_size

            with timer("Time/env_interaction_time"):
                with placement.ctx():
                    # prepare_obs is numpy; PRNG split runs inside the jit —
                    # one dispatch, one host fetch per step.
                    np_obs = prepare_obs(next_obs, mlp_keys=obs_keys, num_envs=cfg.env.num_envs)
                    *step_out, rollout_key = player_step_fn(
                        placement.params(), np_obs, rollout_key
                    )
                    # Structural per-step sync (actions feed env.step):
                    # submitted at dispatch, harvested at the use site.
                    pending = pipeline.fetch(step_out, label="player_actions")
                actions, real_actions_np, logprobs, values = pending.harvest()

                obs, rewards, terminated, truncated, info = envs.step(
                    real_actions_np.reshape(envs.action_space.shape)
                )
                truncated_envs = np.nonzero(truncated)[0]
                if len(truncated_envs) > 0:
                    final_obs = info["final_obs"]
                    real_next_obs = {
                        k: np.stack([np.asarray(final_obs[e][k], np.float32) for e in truncated_envs])
                        for k in obs_keys
                    }
                    with placement.ctx():
                        jnp_next = prepare_obs(real_next_obs, mlp_keys=obs_keys, num_envs=len(truncated_envs))
                        vals_pending = pipeline.fetch(
                            get_values_fn(placement.params(), jnp_next), label="trunc_bootstrap"
                        )
                    vals = np.asarray(vals_pending.harvest())
                    rewards[truncated_envs] += cfg.algo.gamma * vals.reshape(rewards[truncated_envs].shape)
                dones = np.logical_or(terminated, truncated).reshape(cfg.env.num_envs, -1).astype(np.uint8)
                rewards = rewards.reshape(cfg.env.num_envs, -1).astype(np.float32)

            step_data["dones"] = dones[np.newaxis]
            step_data["values"] = values[np.newaxis]
            step_data["actions"] = actions[np.newaxis]
            step_data["logprobs"] = logprobs[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            # returns/advantages are computed INSIDE the train jit now — no
            # buffer placeholders, no host round-trip.

            rb.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs = {}
            for k in obs_keys:
                step_data[k] = obs[k][np.newaxis]
                next_obs[k] = obs[k]

            if cfg.metric.log_level > 0 and "final_info" in info:
                fi = info["final_info"]
                for i in np.nonzero(fi.get("_episode", []))[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        # Ship the rollout ((T, E) tensors env-sharded — core/rollout.py);
        # the whole update is then ONE dispatch.
        local_data = rb.to_tensor()
        next_obs_np = prepare_obs(next_obs, mlp_keys=obs_keys, num_envs=cfg.env.num_envs)
        data, jnp_next = ship_rollout(
            runtime,
            local_data,
            (*obs_keys, "actions"),
            next_obs_np,
            share_data=bool(cfg.buffer.get("share_data", False)),
        )

        with timer("Time/train_time"):
            with train_timer.step():
                params, opt_state, train_metrics, train_key = train_fn(
                    params, opt_state, data, jnp_next, train_key
                )
            # No sync here: the StepTimer queues the loss scalars device-side
            # and bounds the interval with ONE block at the flush below.
            train_timer.pend(params, train_metrics if keep_train_metrics else None)
        placement.push(params)
        train_step_count += world_size

        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            # ONE bounding block + ONE device->host transfer for the whole
            # interval (StepTimer.flush) — the coalesced GL002 pattern.
            fetched_train_metrics = train_timer.flush()
            # Health sentinels inspect the same coalesced fetch — no extra
            # transfer; a nonfinite hit taints the run and escalates.
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            if aggregator and not aggregator.disabled:
                for tm in fetched_train_metrics:
                    aggregator.update("Loss/policy_loss", tm["policy_loss"])
                    aggregator.update("Loss/value_loss", tm["value_loss"])
                # Collective when sync_on_compute is on: every rank joins;
                # only rank 0 (the only rank with a logger) writes.
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if cfg.metric.log_level > 0 and logger is not None:
            if should_log:
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log(
                            "Time/sps_train",
                            (train_step_count - last_train) / timer_metrics["Time/train_time"],
                            policy_step,
                        )
                    if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log(
                            "Time/sps_env_interaction",
                            ((policy_step - last_log) / world_size * cfg.env.action_repeat)
                            / timer_metrics["Time/env_interaction_time"],
                            policy_step,
                        )
                    timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        if cfg.algo.anneal_lr:
            new_lr = polynomial_decay(iter_num, initial=base_lr, final=0.0, max_decay_steps=total_iters, power=1.0)
            opt_state.hyperparams["lr"] = jnp.asarray(new_lr, jnp.float32)

        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "optimizer": opt_state,
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break
    pipeline.publish()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, params, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
