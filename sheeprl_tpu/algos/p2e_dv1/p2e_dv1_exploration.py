"""Plan2Explore (DreamerV1) — exploration phase
(reference: sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py:41-801).

DreamerV1's jitted gradient step extended with the P2E phases: the ensemble
update (members regress the next OBSERVATION EMBEDDING; vmapped over stacked
params), an exploration actor/critic trained purely on ensemble-disagreement
reward, and the zero-shot task actor/critic on extrinsic reward. DV1-style
behaviour losses throughout: pure dynamics-backprop actor objective
(-mean(discount * lambda)) and Normal(.,1) critics without targets.
"""

from __future__ import annotations

import copy
import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v1.agent import DV1WorldModel
from sheeprl_tpu.algos.dreamer_v1.loss import actor_loss, critic_loss, reconstruction_loss
from sheeprl_tpu.algos.dreamer_v1.utils import compute_lambda_values, exploration_amount
from sheeprl_tpu.algos.dreamer_v2.agent import dv2_actor_forward
from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import _make_optimizer
from sheeprl_tpu.algos.p2e_dv1.agent import P2EDV1Agent, build_agent
from sheeprl_tpu.algos.p2e_dv1.utils import normalize_player_obs, prepare_obs, test
from sheeprl_tpu.algos.ppo.agent import actions_metadata
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.core.interact import InteractionPipeline
from sheeprl_tpu.core.mesh import DATA_AXIS
from sheeprl_tpu.core.player import PlayerPlacement
from sheeprl_tpu.data.infeed import ReplayInfeed
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.core.runtime import DispatchThrottle
from sheeprl_tpu.registry import register_algorithm
from sheeprl_tpu.utils.checkpoint import load_checkpoint, restore_opt_state, save_checkpoint
from sheeprl_tpu.utils.distribution import BernoulliSafeMode, Independent, MSEDistribution, Normal
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


def make_train_step(agent: P2EDV1Agent, txs: Dict[str, Any], cfg: Dict[str, Any], mesh):
    """Build the jitted P2E-DV1 gradient step over a [T, B] batch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    stochastic_size = int(wm_cfg.stochastic_size)
    recurrent_state_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    use_continues = bool(wm_cfg.use_continues)
    intrinsic_multiplier = float(cfg.algo.intrinsic_reward_multiplier)
    spec = agent.actor_spec
    dv1 = agent.dv1

    batch_sharding = NamedSharding(mesh, P(None, DATA_AXIS))

    def world_loss_fn(wm_params, data, batch_obs, keys):
        T, B = data["rewards"].shape[:2]
        embedded = dv1.wm(wm_params, batch_obs, method="embed_obs")
        h0 = jnp.zeros((B, recurrent_state_size), embedded.dtype)
        z0 = jnp.zeros((B, stochastic_size), embedded.dtype)

        def step(carry, x):
            h, z = carry
            action, emb, key = x
            h, post, prior, post_ms, prior_ms = dv1.world_model.apply(
                wm_params, z, h, action, emb, key, method=DV1WorldModel.dynamic
            )
            return (h, post), (h, post, post_ms[0], post_ms[1], prior_ms[0], prior_ms[1])

        (_, _), (recurrent_states, posteriors, post_means, post_stds, prior_means, prior_stds) = (
            jax.lax.scan(step, (h0, z0), (data["actions"], embedded, keys))
        )
        latent_states = jnp.concatenate([posteriors, recurrent_states], -1)

        reconstructed_obs = dv1.wm(wm_params, latent_states, method="decode")
        qo = {
            k: Independent(Normal(v, jnp.ones_like(v)), len(v.shape[2:]))
            for k, v in reconstructed_obs.items()
        }
        qr = Independent(Normal(dv1.wm(wm_params, latent_states, method="reward"), 1.0), 1)
        if use_continues:
            qc = Independent(
                BernoulliSafeMode(logits=dv1.wm(wm_params, latent_states, method="continue_logits")), 1
            )
            continues_targets = (1 - data["terminated"]) * gamma
        else:
            qc = continues_targets = None

        posteriors_dist = Independent(Normal(post_means, post_stds), 1)
        priors_dist = Independent(Normal(prior_means, prior_stds), 1)
        rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
            qo, batch_obs, qr, data["rewards"], posteriors_dist, priors_dist,
            wm_cfg.kl_free_nats, wm_cfg.kl_regularizer, qc, continues_targets,
            wm_cfg.continue_scale_factor,
        )
        aux = {
            "posteriors": posteriors,
            "recurrent_states": recurrent_states,
            "embedded": embedded,
            "post_entropy": posteriors_dist.entropy().mean(),
            "prior_entropy": priors_dist.entropy().mean(),
            "kl": kl,
            "state_loss": state_loss,
            "reward_loss": reward_loss,
            "observation_loss": observation_loss,
            "continue_loss": continue_loss,
        }
        return rec_loss, aux

    def imagine_rollout(actor_params, wm_params, prior0, h0, latent0, k_img):
        """DV1-style rollout: action i is sampled FROM state i-1 and the
        trajectory excludes the seed latent. Returns ([H, TB, L], [H, TB, A])."""
        sg = jax.lax.stop_gradient

        def actor_sample(latent, k):
            pre = dv1.actor.apply(actor_params, sg(latent))
            actions, _ = dv2_actor_forward(pre, spec, k, greedy=False)
            return jnp.concatenate(actions, -1)

        def img_step(carry, k):
            prior, h, latent = carry
            k_act, k_wm = jax.random.split(k)
            actions = actor_sample(latent, k_act)
            prior, h = dv1.world_model.apply(
                wm_params, prior, h, actions, k_wm, method=DV1WorldModel.imagination
            )
            latent = jnp.concatenate([prior, h], -1)
            return (prior, h, latent), (latent, actions)

        _, (latents, img_actions) = jax.lax.scan(
            img_step, (prior0, h0, latent0), jax.random.split(k_img, horizon)
        )
        return latents, img_actions

    def imagined_continues(wm_params, trajectories, like):
        if use_continues:
            return jax.nn.sigmoid(dv1.wm(wm_params, trajectories, method="continue_logits"))
        return jnp.ones_like(like) * gamma

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(state, opt_states, data, key):
        next_key, key = jax.random.split(key)
        T, B = data["rewards"].shape[:2]
        data = jax.lax.with_sharding_constraint(data, {k: batch_sharding for k in data})
        batch_obs = {k: data[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k] for k in mlp_keys})
        sg = jax.lax.stop_gradient

        k_dyn, kimg_expl, kimg_task = jax.random.split(key, 3)
        dyn_keys = jax.random.split(k_dyn, T)

        # ---------------------------------------------- world model update
        (rec_loss, aux), wm_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(
            state["world_model"], data, batch_obs, dyn_keys
        )
        wm_updates, wm_opt = txs["world_model"].update(
            wm_grads, opt_states["world_model"], state["world_model"]
        )
        state["world_model"] = optax.apply_updates(state["world_model"], wm_updates)

        posteriors = sg(aux["posteriors"])
        recurrent_states = sg(aux["recurrent_states"])
        embedded = sg(aux["embedded"])

        # ------------------------------------------------------- ensembles
        def ensemble_loss_fn(ens_params):
            # Only the first T-1 timesteps have a next-step target: slice
            # before the forward pass, not after.
            x = jnp.concatenate([posteriors, recurrent_states, sg(data["actions"])], -1)[:-1]
            preds = agent.ensemble_apply(ens_params, x)  # [N, T-1, B, E]
            target = embedded[1:]

            def member_loss(pred):
                return -Independent(Normal(pred, 1.0), 1).log_prob(target).mean()

            return jax.vmap(member_loss)(preds).sum()

        ensemble_loss, ens_grads = jax.value_and_grad(ensemble_loss_fn)(state["ensembles"])
        ens_updates, ens_opt = txs["ensembles"].update(ens_grads, opt_states["ensembles"], state["ensembles"])
        state["ensembles"] = optax.apply_updates(state["ensembles"], ens_updates)

        prior0 = posteriors.reshape(-1, stochastic_size)
        h0 = recurrent_states.reshape(-1, recurrent_state_size)
        latent0 = jnp.concatenate([prior0, h0], -1)

        # --------------------------------------- exploration behaviour
        def expl_loss_fn(actor_params):
            trajectories, imagined_actions = imagine_rollout(
                actor_params, state["world_model"], prior0, h0, latent0, kimg_expl
            )
            ens_in = jnp.concatenate([sg(trajectories), sg(imagined_actions)], -1)
            next_obs_pred = agent.ensemble_apply(state["ensembles"], ens_in)
            intrinsic_reward = (
                next_obs_pred.var(0).mean(-1, keepdims=True) * intrinsic_multiplier
            )
            values = dv1.critic_value(state["critic_exploration"], trajectories)
            continues = imagined_continues(state["world_model"], trajectories, sg(intrinsic_reward))
            lambda_values = compute_lambda_values(
                intrinsic_reward, values, continues, last_values=values[-1], lmbda=lmbda
            )
            discount = sg(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], 0), 0)
            )
            policy_loss = actor_loss(discount * lambda_values)
            aux_expl = {
                "trajectories": sg(trajectories),
                "lambda_values": sg(lambda_values),
                "discount": discount,
                "mean_intrinsic": sg(intrinsic_reward).mean(),
            }
            return policy_loss, aux_expl

        (policy_loss_expl, aux_expl), ae_grads = jax.value_and_grad(expl_loss_fn, has_aux=True)(
            state["actor_exploration"]
        )
        ae_updates, ae_opt = txs["actor_exploration"].update(
            ae_grads, opt_states["actor_exploration"], state["actor_exploration"]
        )
        state["actor_exploration"] = optax.apply_updates(state["actor_exploration"], ae_updates)

        def expl_critic_loss_fn(params):
            qv = Independent(
                Normal(dv1.critic_value(params, aux_expl["trajectories"][:-1]), 1.0), 1
            )
            return critic_loss(qv, aux_expl["lambda_values"], aux_expl["discount"][..., 0])

        value_loss_expl, ce_grads = jax.value_and_grad(expl_critic_loss_fn)(
            state["critic_exploration"]
        )
        ce_updates, ce_opt = txs["critic_exploration"].update(
            ce_grads, opt_states["critic_exploration"], state["critic_exploration"]
        )
        state["critic_exploration"] = optax.apply_updates(state["critic_exploration"], ce_updates)

        # ------------------------------------------------ task behaviour
        def task_loss_fn(actor_params):
            trajectories, _ = imagine_rollout(
                actor_params, state["world_model"], prior0, h0, latent0, kimg_task
            )
            values = dv1.critic_value(state["critic_task"], trajectories)
            rewards = dv1.wm(state["world_model"], trajectories, method="reward")
            continues = imagined_continues(state["world_model"], trajectories, sg(rewards))
            lambda_values = compute_lambda_values(
                rewards, values, continues, last_values=values[-1], lmbda=lmbda
            )
            discount = sg(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], 0), 0)
            )
            policy_loss = actor_loss(discount * lambda_values)
            aux_task = {
                "trajectories": sg(trajectories),
                "lambda_values": sg(lambda_values),
                "discount": discount,
            }
            return policy_loss, aux_task

        (policy_loss_task, aux_task), at_grads = jax.value_and_grad(task_loss_fn, has_aux=True)(
            state["actor_task"]
        )
        at_updates, at_opt = txs["actor_task"].update(
            at_grads, opt_states["actor_task"], state["actor_task"]
        )
        state["actor_task"] = optax.apply_updates(state["actor_task"], at_updates)

        def task_critic_loss_fn(params):
            qv = Independent(
                Normal(dv1.critic_value(params, aux_task["trajectories"][:-1]), 1.0), 1
            )
            return critic_loss(qv, aux_task["lambda_values"], aux_task["discount"][..., 0])

        value_loss_task, ct_grads = jax.value_and_grad(task_critic_loss_fn)(state["critic_task"])
        ct_updates, ct_opt = txs["critic_task"].update(
            ct_grads, opt_states["critic_task"], state["critic_task"]
        )
        state["critic_task"] = optax.apply_updates(state["critic_task"], ct_updates)

        opt_states = {
            "world_model": wm_opt,
            "actor_task": at_opt,
            "critic_task": ct_opt,
            "actor_exploration": ae_opt,
            "critic_exploration": ce_opt,
            "ensembles": ens_opt,
        }
        metrics = {
            "Loss/world_model_loss": rec_loss,
            "Loss/observation_loss": aux["observation_loss"],
            "Loss/reward_loss": aux["reward_loss"],
            "Loss/state_loss": aux["state_loss"],
            "Loss/continue_loss": aux["continue_loss"],
            "Loss/ensemble_loss": ensemble_loss,
            "State/kl": aux["kl"],
            "State/post_entropy": aux["post_entropy"],
            "State/prior_entropy": aux["prior_entropy"],
            "Loss/policy_loss_exploration": policy_loss_expl,
            "Loss/value_loss_exploration": value_loss_expl,
            "Loss/policy_loss_task": policy_loss_task,
            "Loss/value_loss_task": value_loss_task,
            "Rewards/intrinsic": aux_expl["mean_intrinsic"],
            "Grads/world_model": optax.global_norm(wm_grads),
            "Grads/actor_task": optax.global_norm(at_grads),
            "Grads/critic_task": optax.global_norm(ct_grads),
            "Grads/actor_exploration": optax.global_norm(ae_grads),
            "Grads/critic_exploration": optax.global_norm(ce_grads),
            "Grads/ensemble": optax.global_norm(ens_grads),
        }
        return state, opt_states, metrics, next_key

    return train_step


@register_algorithm(name="p2e_dv1_exploration")
def main(runtime, cfg: Dict[str, Any]):
    rank = runtime.global_rank
    world_size = jax.process_count()

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference: dreamer_v1.py:398-400)
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir}")
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    health = runtime.health

    envs = make_vector_env(cfg, rank, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    actions_dim, is_continuous = actions_metadata(action_space)
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    # Eager flax/optax init runs host-side (each eager dispatch pays the device-link round trip); shard_params then moves the finished trees to the mesh.
    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime,
            actions_dim,
            is_continuous,
            cfg,
            observation_space,
            state_ckpt["world_model"] if state_ckpt is not None else None,
            state_ckpt["ensembles"] if state_ckpt is not None else None,
            state_ckpt["actor_task"] if state_ckpt is not None else None,
            state_ckpt["critic_task"] if state_ckpt is not None else None,
            state_ckpt["actor_exploration"] if state_ckpt is not None else None,
            state_ckpt["critic_exploration"] if state_ckpt is not None else None,
        )

        txs = {
            "world_model": _make_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients),
            "actor_task": _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients),
            "critic_task": _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients),
            "actor_exploration": _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients),
            "critic_exploration": _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients),
            "ensembles": _make_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients),
        }
        opt_states = {
            "world_model": txs["world_model"].init(agent_state["world_model"]),
            "actor_task": txs["actor_task"].init(agent_state["actor_task"]),
            "critic_task": txs["critic_task"].init(agent_state["critic_task"]),
            "actor_exploration": txs["actor_exploration"].init(agent_state["actor_exploration"]),
            "critic_exploration": txs["critic_exploration"].init(agent_state["critic_exploration"]),
            "ensembles": txs["ensembles"].init(agent_state["ensembles"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (
                ("world_model", "world_optimizer"),
                ("actor_task", "actor_task_optimizer"),
                ("critic_task", "critic_task_optimizer"),
                ("actor_exploration", "actor_exploration_optimizer"),
                ("critic_exploration", "critic_exploration_optimizer"),
                ("ensembles", "ensemble_optimizer"),
            ):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])

    agent_state = runtime.shard_params(agent_state)
    opt_states = runtime.shard_params(opt_states)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(cfg.env.num_envs * world_size) if not cfg.dry_run else 2
    rb = EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=cfg.env.num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        buffer_cls=SequentialReplayBuffer,
    )
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        rb = state_ckpt["rb"]

    train_step_count = 0
    last_train = 0
    start_iter = (state_ckpt["iter_num"] // world_size) + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * cfg.env.num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    train_fn = make_train_step(agent, txs, cfg, runtime.mesh)
    player_cnn_keys = tuple(cfg.algo.cnn_keys.encoder)

    def _player_step(wm, a, s, o, k, amount):
        # PRNG split + obs normalization in-graph: ONE dispatch per env step.
        next_k, sub = jax.random.split(k)
        out = agent.dv1.player_step(
            wm, a, s, normalize_player_obs(o, player_cnn_keys), sub, greedy=False, expl_amount=amount
        )
        return (*out, next_k)

    player_step_fn = jax.jit(_player_step
    )
    init_player_fn = jax.jit(agent.dv1.init_player_state, static_argnums=(1,))
    reset_player_fn = jax.jit(agent.dv1.reset_player_state)
    player_actor_key = (
        "actor_exploration" if cfg.algo.player.actor_type == "exploration" else "actor_task"
    )

    # Latency-aware player placement (core/player.py); off-policy: honors
    # fabric.player_sync=async. Mirror = world model + the player's actor.
    placement = PlayerPlacement.resolve(
        cfg, runtime.mesh.devices.flat[0],
        params={"world_model": agent_state["world_model"], "actor": agent_state[player_actor_key]},
    )
    placement.push(
        {"world_model": agent_state["world_model"], "actor": agent_state[player_actor_key]}
    )


    # Async infeed (data/infeed.py): the next train call's sampled batches
    # are copied host->device by a worker thread while envs step, so the
    # pixel-batch H2D never sits on the critical path.
    infeed = ReplayInfeed(
        rb,
        cfg.algo.per_rank_batch_size,
        cfg.algo.per_rank_sequence_length,
        cfg.algo.cnn_keys.encoder,
        enabled=cfg.buffer.get("prefetch", True),
    )

    rollout_key, train_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key = placement.put(rollout_key)

    # Async-capable action fetch (core/interact.py): with fabric.async_fetch
    # the D2H copy is submitted at dispatch time and harvested right before
    # envs.step; off it is op-for-op the old blocking fetch.
    pipeline = InteractionPipeline.from_config(cfg)

    step_data = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = obs[k][np.newaxis]
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1), np.float32)
    step_data["actions"] = np.zeros((1, cfg.env.num_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1), np.float32)
    rb.add(step_data, validate_args=cfg.buffer.validate_args)
    with placement.ctx():
        player_state = init_player_fn(placement.params()["world_model"], cfg.env.num_envs)

    cumulative_per_rank_gradient_steps = 0
    # Bound async in-flight train dispatches (core/runtime.py: an
    # unbounded queue pins every pending call's sampled batch on host).
    dispatch_throttle = DispatchThrottle()
    # Coalesced loss fetch + interval bounding (telemetry/step_timer.py):
    # ONE block_until_ready + ONE device_get per log interval.
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    keep_train_metrics = (
        aggregator is not None and not aggregator.disabled and cfg.metric.log_level > 0
    ) or health.enabled
    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter
        telemetry.advance(policy_step)
        guard.advance(policy_step)

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and cfg.checkpoint.resume_from is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                with placement.ctx():
                    np_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=cfg.env.num_envs)
                    amount = exploration_amount(agent.actor_spec, policy_step)
                    pp = placement.params()
                    actions_cat, real_actions_j, player_state, rollout_key = player_step_fn(
                        pp["world_model"],
                        pp["actor"],
                        player_state,
                        np_obs,
                        rollout_key,
                        np.asarray(amount, np.float32),
                    )
                # One host fetch for both arrays: each separate np.asarray
                # is a full device->host roundtrip (painful over a tunneled
                # chip). Submitted at dispatch, harvested at the last moment
                # so the copy rides under the host bookkeeping in between.
                pending = pipeline.fetch((actions_cat, real_actions_j), label="player_actions")
                if aggregator and not aggregator.disabled:
                    aggregator.update("Params/exploration_amount", amount)
                actions, real_actions = pending.harvest()

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            for i in np.nonzero(fi.get("_episode", []))[0]:
                ep_rew = float(fi["episode"]["r"][i])
                ep_len = float(fi["episode"]["l"][i])
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        real_next_obs = copy.deepcopy(next_obs)
        if "final_obs" in infos:
            for idx in np.nonzero(dones)[0]:
                final = infos["final_obs"][idx]
                if final is not None:
                    for k, v in final.items():
                        real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = real_next_obs[k][np.newaxis]
        obs = next_obs

        step_data["terminated"] = terminated.reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        step_data["truncated"] = truncated.reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        step_data["rewards"] = clip_rewards_fn(rewards).reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = np.zeros((1, reset_envs, 1), np.float32)
            reset_data["truncated"] = np.zeros((1, reset_envs, 1), np.float32)
            reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, reset_envs, 1), np.float32)
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            for d in dones_idxes:
                step_data["terminated"][0, d] = np.zeros_like(step_data["terminated"][0, d])
                step_data["truncated"][0, d] = np.zeros_like(step_data["truncated"][0, d])
            reset_mask = np.zeros((cfg.env.num_envs,), np.float32)
            reset_mask[dones_idxes] = 1.0
            with placement.ctx():
                player_state = reset_player_fn(
                    placement.params()["world_model"], player_state, jnp.asarray(reset_mask)
                )

        # ------------------------------------------------------- training
        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                batches = infeed.take_or_sample(per_rank_gradient_steps)
                with timer("Time/train_time"):
                    for i in range(per_rank_gradient_steps):
                        batch = batches[i]
                        with train_timer.step():
                            agent_state, opt_states, train_metrics, train_key = train_fn(
                                agent_state, opt_states, batch, train_key
                            )
                        # No sync here: the StepTimer queues the loss
                        # scalars device-side and bounds the interval with
                        # ONE block at the log-interval flush.
                        train_timer.pend(
                            agent_state["world_model"],
                            train_metrics if keep_train_metrics else None,
                        )
                        dispatch_throttle.add(train_metrics)
                        cumulative_per_rank_gradient_steps += 1
                    placement.push(
                        {"world_model": agent_state["world_model"], "actor": agent_state[player_actor_key]}
                    )
                    train_step_count += world_size
                # Sample on the main thread (no buffer race); stage the device
                # copies to overlap the next env-step phase.
                infeed.stage(per_rank_gradient_steps)


        # -------------------------------------------------------- logging
        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            # The interval's losses in ONE bounding block + ONE device->host
            # transfer (StepTimer.flush) — the coalesced pattern GL002 asks
            # for, now owned by telemetry.
            fetched_train_metrics = train_timer.flush()
            # Health sentinels inspect the same coalesced fetch — no extra
            # transfer; a nonfinite hit taints the run and escalates.
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            if aggregator and not aggregator.disabled:
                for m in fetched_train_metrics:
                    for k, v in m.items():
                        if k in aggregator:
                            aggregator.update(k, v)
                # Collective when sync_on_compute is on: every rank joins;
                # only rank 0 (the only rank with a logger) writes.
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            if policy_step > 0:
                logger.log(
                    "Params/replay_ratio",
                    cumulative_per_rank_gradient_steps * world_size / policy_step,
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log(
                        "Time/sps_env_interaction",
                        ((policy_step - last_log) / world_size * cfg.env.action_repeat)
                        / timer_metrics["Time/env_interaction_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        # ----------------------------------------------------- checkpoint
        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": agent_state["world_model"],
                "actor_task": agent_state["actor_task"],
                "critic_task": agent_state["critic_task"],
                "actor_exploration": agent_state["actor_exploration"],
                "critic_exploration": agent_state["critic_exploration"],
                "ensembles": agent_state["ensembles"],
                "world_optimizer": opt_states["world_model"],
                "actor_task_optimizer": opt_states["actor_task"],
                "critic_task_optimizer": opt_states["critic_task"],
                "actor_exploration_optimizer": opt_states["actor_exploration"],
                "critic_exploration_optimizer": opt_states["critic_exploration"],
                "ensemble_optimizer": opt_states["ensembles"],
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            if cfg.buffer.checkpoint:
                ckpt_state["rb"] = rb
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break
    infeed.close()
    pipeline.publish()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(
            agent.dv1,
            {"world_model": agent_state["world_model"], "actor": agent_state[player_actor_key]},
            runtime,
            cfg,
            log_dir,
            logger,
        )

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
