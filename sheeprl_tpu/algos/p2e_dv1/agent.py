"""Plan2Explore on DreamerV1: agent construction
(reference: sheeprl/algos/p2e_dv1/agent.py:30-155).

Task side is the DV1 agent unchanged; P2E adds an exploration actor, an
exploration critic (no target network in DV1), and the vmapped disagreement
ensemble. DV1's ensemble members predict the next OBSERVATION EMBEDDING
(encoder output) rather than the next stochastic state — the embedding size
is probed with one dummy encoder application at build time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.agent import DV1Agent, build_agent as dv1_build_agent
from sheeprl_tpu.algos.dreamer_v3.agent import trunc_normal_init
from sheeprl_tpu.models import MLP


@dataclass(frozen=True)
class P2EDV1Agent:
    dv1: DV1Agent
    ensemble: MLP
    n_ensembles: int

    @property
    def actor(self):
        return self.dv1.actor

    @property
    def world_model(self):
        return self.dv1.world_model

    @property
    def actor_spec(self):
        return self.dv1.actor_spec

    @property
    def actions_dim(self):
        return self.dv1.actions_dim

    def ensemble_apply(self, stacked_params, x: jax.Array) -> jax.Array:
        return jax.vmap(lambda p: self.ensemble.apply(p, x))(stacked_params)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Any] = None,
    critic_task_state: Optional[Any] = None,
    actor_exploration_state: Optional[Any] = None,
    critic_exploration_state: Optional[Any] = None,
) -> Tuple[P2EDV1Agent, Dict[str, Any]]:
    dv1_agent, dv1_state = dv1_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
    )
    wm_cfg = cfg.algo.world_model
    latent_state_size = int(wm_cfg.stochastic_size) + int(wm_cfg.recurrent_model.recurrent_state_size)
    dtype = runtime.precision.compute_dtype

    # Probe the encoder embedding size (the ensemble's regression target).
    dummy_obs = {
        k: jnp.zeros((1, *obs_space[k].shape), jnp.float32)
        for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    }
    embed_dim = int(
        dv1_agent.wm(dv1_state["world_model"], dummy_obs, method="embed_obs").shape[-1]
    )

    ens_cfg = cfg.algo.ensembles
    ensemble = MLP(
        hidden_sizes=[int(ens_cfg.dense_units)] * int(ens_cfg.mlp_layers),
        output_dim=embed_dim,
        activation="elu",
        kernel_init=trunc_normal_init,
        dtype=dtype,
    )
    agent = P2EDV1Agent(dv1=dv1_agent, ensemble=ensemble, n_ensembles=int(ens_cfg.n))

    k_actor_expl, k_critic_expl, k_ens = jax.random.split(jax.random.fold_in(runtime.root_key, 3), 3)
    dummy_latent = jnp.zeros((1, latent_state_size), jnp.float32)

    if actor_exploration_state is not None:
        actor_expl_params = jax.tree_util.tree_map(jnp.asarray, actor_exploration_state)
    else:
        actor_expl_params = dv1_agent.actor.init(k_actor_expl, dummy_latent)

    if critic_exploration_state is not None:
        critic_expl_params = jax.tree_util.tree_map(jnp.asarray, critic_exploration_state)
    else:
        critic_expl_params = dv1_agent.critic.init(k_critic_expl, dummy_latent)

    ens_in = int(np.sum(actions_dim)) + latent_state_size
    if ensembles_state is not None:
        ens_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)
    else:
        dummy_ens = jnp.zeros((1, ens_in), jnp.float32)
        ens_params = jax.vmap(lambda k: ensemble.init(k, dummy_ens))(
            jax.random.split(k_ens, int(ens_cfg.n))
        )

    state = {
        "world_model": dv1_state["world_model"],
        "actor_task": dv1_state["actor"],
        "critic_task": dv1_state["critic"],
        "actor_exploration": actor_expl_params,
        "critic_exploration": critic_expl_params,
        "ensembles": ens_params,
    }
    return agent, state
