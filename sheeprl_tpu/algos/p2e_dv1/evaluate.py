"""P2E-DV1 evaluation entrypoint (reference: sheeprl/algos/p2e_dv1/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as dv1_build_agent
from sheeprl_tpu.algos.dreamer_v1.utils import test
from sheeprl_tpu.algos.ppo.agent import actions_metadata
from sheeprl_tpu.registry import register_evaluation
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger


@register_evaluation(algorithms=["p2e_dv1_exploration", "p2e_dv1_finetuning"])
def evaluate_p2e_dv1(runtime, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir}")

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    observation_space = env.observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    actions_dim, is_continuous = actions_metadata(env.action_space)
    env.close()

    agent, agent_state = dv1_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"],
        state["actor_task"],
        state["critic_task"],
    )
    if cfg.algo.player.actor_type == "exploration":
        agent_state["actor"] = jax.tree_util.tree_map(jnp.asarray, state["actor_exploration"])
    test(agent, agent_state, runtime, cfg, log_dir, logger)
