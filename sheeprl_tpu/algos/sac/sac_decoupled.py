"""SAC, decoupled player/trainer loop (reference: sheeprl/algos/sac/sac_decoupled.py:33-588).

TPU-native redesign, not a port. The reference splits player and trainers
across *processes*: rank 0 steps the envs and owns the replay buffer, ranks
1..N-1 form a DDP optimization group; `scatter_object_list` ships sampled
batches player->trainers and a flat-parameter broadcast ships actor weights
trainers->player every iteration.

Here both partitions live in ONE controller process over a partitioned device
set: device 0 is the *player device*, devices 1..N-1 form the *trainer mesh*.
The object-list collectives become device-to-device transfers:

- batches: host sample -> `device_put` sharded over the trainer mesh's data
  axis (the scatter),
- weights: `device_put(actor_params, player_device)` after each train call
  (the broadcast).

Dispatch is async: the controller enqueues the G-step train scan on the
trainer devices and immediately enqueues the actor-weight copy; the player's
next inference waits only on that copy, and host env stepping overlaps trainer
compute. The pipelining the reference builds out of processes and blocking
collectives falls out of XLA's asynchronous dispatch.
"""

from __future__ import annotations

import copy
import os
import time
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import _make_optimizer, make_train_step
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.core.player import ParamMirror
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.core import fleet as fleet_lib
from sheeprl_tpu.core import mesh as mesh_lib
from sheeprl_tpu.core.mesh import DATA_AXIS, split_player_trainer
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.core.runtime import DispatchThrottle
from sheeprl_tpu.registry import register_algorithm
from sheeprl_tpu.utils.checkpoint import (
    load_checkpoint,
    load_recorded_shardings,
    place_with_recorded_shardings,
    restore_opt_state,
    save_checkpoint,
)
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


@register_algorithm(decoupled=True)
def main(runtime, cfg: Dict[str, Any]):
    # The player/trainer split happens after the agent is built, so the
    # auto placement's AUTO_MAX_PARAM_BYTES guard sees the real actor size.
    player_mode = cfg.fabric.get("player_device", "auto") or "auto"
    rank = runtime.global_rank

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    telemetry.set_run_info(algo="sac_decoupled", rank=rank)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    health = runtime.health
    runtime.print(f"Log dir: {log_dir}")

    # ------------------------------------------------------------ environment
    # Fleet mode moves env stepping into supervised actor-replica processes
    # (core/fleet.py); the learner keeps one short-lived local vector env
    # purely as the space probe its agent/validation code keys off.
    use_fleet = fleet_lib.fleet_active(cfg)
    envs = make_vector_env(cfg, rank, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}. "
                f"Provided environment: {cfg.env.id}"
            )
    if cfg.metric.log_level > 0:
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    fleet_sup = None
    if use_fleet:
        envs.close()  # the probe served its purpose; replicas own the envs
        fleet_sup = fleet_lib.FleetSupervisor.from_config(
            cfg,
            "sheeprl_tpu.algos.sac.fleet_actor:actor_loop",
            seed=int(cfg.seed),
            log_dir=log_dir,
        )
        fleet_sup.start()
        runtime.print(
            f"Fleet: {fleet_sup.replicas} actor replica(s), quorum {int(cfg.fleet.quorum)}"
        )

    # ------------------------------------------------------- agent + optimizers
    # Eager flax/optax init runs host-side (each eager dispatch pays the
    # device-link round trip); replicate() then moves the trees to the mesh.
    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime, cfg, observation_space, action_space,
            state_ckpt["agent"] if state_ckpt is not None else None,
        )

        txs = {
            "qf": _make_optimizer(cfg.algo.critic.optimizer),
            "actor": _make_optimizer(cfg.algo.actor.optimizer),
            "alpha": _make_optimizer(cfg.algo.alpha.optimizer),
        }
        opt_states = {
            "qf": txs["qf"].init(agent_state["qfs"]),
            "actor": txs["actor"].init(agent_state["actor"]),
            "alpha": txs["alpha"].init(agent_state["log_alpha"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (("qf", "qf_optimizer"), ("actor", "actor_optimizer"), ("alpha", "alpha_optimizer")):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])

        # Trainer state lives replicated on the trainer mesh; the player keeps its
        # own committed copy of the actor params on the player device (the
        # "first weights" broadcast of the reference, sac_decoupled.py:227-230).
    # Split now that the player-visible actor exists: auto applies its size
    # guard (an oversized actor stays on-mesh rather than paying a packed
    # host transfer after every update).
    player_device, trainer_mesh = split_player_trainer(
        runtime.mesh, player_mode, params=agent_state["actor"]
    )
    n_trainers = int(trainer_mesh.shape[DATA_AXIS])
    runtime.print(f"Decoupled SAC: player on {player_device}, {n_trainers} trainer device(s)")
    # shard_wide_params == replicate when model_axis is 1; with a model
    # axis it shards wide dense stacks tensor-parallel over the trainers.
    # A resumed run prefers the checkpoint manifest's recorded per-leaf
    # shardings (utils/checkpoint.py): the layout intent of the saving mesh,
    # replayed against THIS mesh — the elastic-resume path that makes an
    # 8-device save restart bit-compatibly on 4 (or 1) devices.
    recorded = (
        load_recorded_shardings(cfg.checkpoint.resume_from)
        if cfg.checkpoint.resume_from
        else None
    )
    if recorded:
        def _wide(leaf):
            return mesh_lib.shard_wide_params(leaf, trainer_mesh)

        agent_state = place_with_recorded_shardings(
            agent_state, recorded, trainer_mesh, prefix="agent", default=_wide
        )
        opt_states = {
            name: place_with_recorded_shardings(
                opt_states[name], recorded, trainer_mesh, prefix=ckpt_key, default=_wide
            )
            for name, ckpt_key in (
                ("qf", "qf_optimizer"),
                ("actor", "actor_optimizer"),
                ("alpha", "alpha_optimizer"),
            )
        }
    else:
        agent_state = mesh_lib.shard_wide_params(agent_state, trainer_mesh)
        opt_states = mesh_lib.shard_wide_params(opt_states, trainer_mesh)
    # Per-shard goodput over the TRAINER partition (the player device is
    # accounted by its own fetch/infeed spans), plus the topology + layout
    # records behind `python -m sheeprl_tpu.telemetry mesh`.
    telemetry.set_mesh(trainer_mesh)
    telemetry.record_param_layouts(agent_state)
    # The trainer->player weight broadcast as a packed single-transfer mirror
    # (core/player.py): honors fabric.player_sync — "fresh" makes the next
    # inference wait for the post-update actor, "async" serves the newest
    # snapshot whose transfer finished (the reference's non-blocking
    # broadcast, sac_decoupled.py:260-263).
    actor_mirror = ParamMirror(
        # Same-silicon passthrough ONLY when the trainer partition is that
        # single device: with more trainer devices the params are replicated
        # over a multi-device mesh and the player needs its own committed
        # copy (a shared multi-device array clashes with the player's
        # single-device inputs inside jit).
        None
        if trainer_mesh.devices.size == 1 and player_device == trainer_mesh.devices.flat[0]
        else player_device,
        sync=str(cfg.fabric.get("player_sync", "fresh") or "fresh"),
    )
    actor_mirror.push(agent_state["actor"])

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    # ------------------------------------------------------------ replay buffer
    buffer_size = cfg.buffer.size // int(cfg.env.num_envs) if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        cfg.env.num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
    )
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        rb = state_ckpt["rb"]

    # ------------------------------------------------------------ counters
    last_train = 0
    train_step_count = 0
    start_iter = state_ckpt["iter_num"] + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * cfg.env.num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // n_trainers
        if not cfg.buffer.checkpoint:
            learning_starts += start_iter
            prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    # The same jitted G-step scan as coupled SAC, compiled over the trainer
    # mesh only (its `data` axis is the trainer partition).
    train_fn = make_train_step(agent, txs, cfg, trainer_mesh)
    def _player(p, o, k):
        next_k, sub = jax.random.split(k)
        return agent.get_actions(p, o, sub, greedy=False), next_k

    player_fn = jax.jit(_player)
    batch_sharding = NamedSharding(trainer_mesh, P(None, DATA_AXIS))
    target_freq_iters = cfg.algo.critic.target_network_frequency // policy_steps_per_iter + 1

    rollout_key, train_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key = jax.device_put(rollout_key, player_device)

    step_data = {}
    obs = envs.reset(seed=cfg.seed)[0] if not use_fleet else None
    fleet_sync_every = max(1, int(cfg.fleet.param_sync_every)) if use_fleet else 0

    cumulative_per_rank_gradient_steps = 0
    # Bound async in-flight train dispatches (core/runtime.py: an
    # unbounded queue pins every pending call's sampled batch on host).
    dispatch_throttle = DispatchThrottle()
    # Coalesced loss fetch + interval bounding (telemetry/step_timer.py):
    # ONE block_until_ready + ONE device_get per log interval.
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    perf = telemetry.perf
    keep_train_metrics = (aggregator is not None and not aggregator.disabled) or health.enabled
    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter
        telemetry.advance(policy_step)
        guard.advance(policy_step)

        if use_fleet:
            # The replicas step the envs; the learner's "env interaction" is
            # one admitted shipment per iteration. Supervision (liveness,
            # restarts, quorum) runs inside recv — the bounded timeout keeps
            # the preemption flag honored even when the whole fleet is quiet.
            with timer("Time/env_interaction_time"), perf.infeed():
                shipment = None
                # A preempted learner still ingests THIS iteration's shipment
                # when the fleet can provide one (bounded grace): the in-place
                # signal handler semantics of the non-fleet path, where the
                # interrupted iteration completes before the final save. That
                # keeps the preempt checkpoint's iter_num/replay position
                # identical to the no-fault run — resume-to-parity, not
                # resume-minus-one-shipment.
                grace = time.monotonic() + 5.0
                while shipment is None:
                    if guard.preempted and (
                        fleet_sup.live_replicas == 0 or time.monotonic() > grace
                    ):
                        break
                    shipment = fleet_sup.recv(timeout=0.5)
            if shipment is not None:
                rb.add(shipment.rows, validate_args=cfg.buffer.validate_args)
                if cfg.metric.log_level > 0:
                    for ep_rew, ep_len in shipment.episodes:
                        if aggregator and not aggregator.disabled:
                            aggregator.update("Rewards/rew_avg", ep_rew)
                            aggregator.update("Game/ep_len_avg", ep_len)
                        runtime.print(
                            f"Rank-0: policy_step={policy_step}, "
                            f"reward_replica_{shipment.replica}={ep_rew}"
                        )
        else:
            with timer("Time/env_interaction_time"), perf.infeed():
                if iter_num <= learning_starts:
                    actions = envs.action_space.sample()
                else:
                    with jax.default_device(player_device):
                        np_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=cfg.env.num_envs)
                        actions_j, rollout_key = player_fn(actor_mirror.get(), np_obs, rollout_key)
                    # Structural per-step sync (actions feed env.step): accounted
                    # through the telemetry fetch.
                    actions = telemetry.fetch(actions_j, label="player_actions")
                next_obs, rewards, terminated, truncated, infos = envs.step(
                    actions.reshape(envs.action_space.shape)
                )
                rewards = rewards.reshape(cfg.env.num_envs, -1)

            if cfg.metric.log_level > 0 and "final_info" in infos:
                fi = infos["final_info"]
                for i in np.nonzero(fi.get("_episode", []))[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                        aggregator.update("Game/ep_len_avg", ep_len)
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

            real_next_obs = copy.deepcopy(next_obs)
            if "final_obs" in infos:
                done_mask = np.logical_or(terminated, truncated)
                for idx in np.nonzero(done_mask)[0]:
                    final = infos["final_obs"][idx]
                    if final is not None:
                        for k, v in final.items():
                            real_next_obs[k][idx] = v
            real_next_obs_cat = np.concatenate([real_next_obs[k] for k in mlp_keys], axis=-1).astype(np.float32)

            step_data["terminated"] = terminated.reshape(1, cfg.env.num_envs, -1).astype(np.uint8)
            step_data["truncated"] = truncated.reshape(1, cfg.env.num_envs, -1).astype(np.uint8)
            step_data["actions"] = actions.reshape(1, cfg.env.num_envs, -1)
            step_data["observations"] = np.concatenate([obs[k] for k in mlp_keys], axis=-1).astype(np.float32)[np.newaxis]
            if not cfg.buffer.sample_next_obs:
                step_data["next_observations"] = real_next_obs_cat[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis].astype(np.float32)
            rb.add(step_data, validate_args=cfg.buffer.validate_args)

            obs = next_obs

        # ------------------------------------------------- trainer partition
        if iter_num >= learning_starts and not (use_fleet and shipment is None):
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / n_trainers)
            if per_rank_gradient_steps > 0:
                # The scatter: one host sample covering every trainer's share,
                # placed directly sharded over the trainer mesh (the reference
                # chunks + scatter_object_list, sac_decoupled.py:243-257).
                global_batch = cfg.algo.per_rank_batch_size * n_trainers
                sample = rb.sample_tensors(
                    batch_size=per_rank_gradient_steps * global_batch,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )
                # Accounted scatter (core/mesh.put_sharded): the H2D bytes
                # land on the transfer ledger, and a layout mismatch would
                # surface as transfer/reshard_events instead of hiding.
                data = mesh_lib.put_sharded(
                    {
                        k: np.asarray(v)
                        .astype(np.float32)
                        .reshape(per_rank_gradient_steps, global_batch, *np.asarray(v).shape[2:])
                        for k, v in sample.items()
                    },
                    batch_sharding,
                )
                with timer("Time/train_time"):
                    do_ema = iter_num % target_freq_iters == 0
                    tau_arr = np.asarray(agent.tau if do_ema else 0.0, np.float32)
                    # Goodput accounting BEFORE the dispatch: arg shape specs
                    # must be captured while the buffers are alive (donated).
                    perf.note(
                        f"train/g{per_rank_gradient_steps}", train_fn,
                        (agent_state, opt_states, data, train_key, tau_arr),
                        steps=per_rank_gradient_steps,
                    )
                    with train_timer.step():
                        agent_state, opt_states, train_metrics, train_key = train_fn(
                            agent_state,
                            opt_states,
                            data,
                            train_key,
                            tau_arr,
                        )
                    # No sync here: the StepTimer queues the loss scalars
                    # device-side and bounds the interval with ONE block at
                    # the log-interval flush.
                    train_timer.pend(
                        agent_state["actor"], train_metrics if keep_train_metrics else None
                    )
                    dispatch_throttle.add(train_metrics)
                    # The broadcast back: enqueue the packed weight copy and
                    # return to env stepping.
                    actor_mirror.push(agent_state["actor"])
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                train_step_count += n_trainers
                if use_fleet and iter_num % fleet_sync_every == 0:
                    # Cross-process weight broadcast: one host pull, fanned
                    # out by the per-replica pump threads (a dead replica's
                    # pump dies with its pipe instead of blocking this call).
                    # copy=True is load-bearing: np.asarray of a CPU jax
                    # array can be a zero-copy view, and the pump threads
                    # pickle asynchronously while the next train step DONATES
                    # these buffers.
                    fleet_sup.push_params(
                        jax.tree_util.tree_map(
                            lambda a: np.array(a, copy=True), agent_state["actor"]
                        ),
                        version=iter_num,
                    )

        # ------------------------------------------------------------ logging
        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            # ONE bounding block + ONE device->host transfer for the whole
            # interval (StepTimer.flush) — the coalesced GL002 pattern.
            fetched_train_metrics = train_timer.flush()
            # Health sentinels inspect the same coalesced fetch — no extra
            # transfer; a nonfinite hit taints the run and escalates.
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            if aggregator and not aggregator.disabled:
                for tm in fetched_train_metrics:
                    aggregator.update("Loss/value_loss", tm["value_loss"])
                    aggregator.update("Loss/policy_loss", tm["policy_loss"])
                    aggregator.update("Loss/alpha_loss", tm["alpha_loss"])
                # Collective when sync_on_compute is on: every rank joins;
                # only rank 0 (the only rank with a logger) writes.
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            if policy_step > 0:
                logger.log(
                    "Params/replay_ratio",
                    cumulative_per_rank_gradient_steps * n_trainers / policy_step,
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log(
                        "Time/sps_env_interaction",
                        ((policy_step - last_log) * cfg.env.action_repeat)
                        / timer_metrics["Time/env_interaction_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        # --------------------------------------------------------- checkpoint
        if health.allow_save() and (
            (
                iter_num >= learning_starts
                and cfg.checkpoint.every > 0
                and policy_step - last_checkpoint >= cfg.checkpoint.every
            )
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            if guard.preempted and use_fleet:
                # Whole-fleet drain BEFORE the final save: replicas get stop,
                # their byes are collected, stragglers' in-flight rows are
                # accounted dropped — then the learner commits and exits.
                fleet_sup.drain_and_stop()
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": agent_state,
                "qf_optimizer": opt_states["qf"],
                "actor_optimizer": opt_states["actor"],
                "alpha_optimizer": opt_states["alpha"],
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "batch_size": cfg.algo.per_rank_batch_size * n_trainers,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            saved_tail = None
            tail = (rb._pos - 1) % rb.buffer_size
            if cfg.buffer.checkpoint:
                # Buffer-tail consistency trick, as in coupled SAC
                # (reference: callback.py:87-142).
                if rb["truncated"] is not None:
                    saved_tail = np.asarray(rb["truncated"][tail, :]).copy()
                    rb["truncated"][tail, :] = 1
                ckpt_state["rb"] = rb
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)
            if saved_tail is not None:
                rb["truncated"][tail, :] = saved_tail

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break
    if use_fleet:
        fleet_sup.close()  # idempotent after a preemption drain
    else:
        envs.close()
    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        # flush: serve the final trained weights, not a stale async snapshot
        test(agent, {"actor": actor_mirror.flush()}, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
