"""SAC, coupled training loop (reference: sheeprl/algos/sac/sac.py:32-427).

TPU-first structure:
- Per iteration: one vectorized env step (host), then `G = Ratio(policy_steps)`
  gradient steps executed inside ONE jitted call — a `lax.scan` over G
  pre-sampled minibatches with the three optimizer states (critic, actor,
  alpha) in the carry. The reference's per-minibatch python loop with three
  backward/step calls (sac.py:32-80) becomes one compiled program.
- The target-EMA cadence (every target_network_frequency policy steps,
  sac.py:56-57) is a traced scalar: tau_eff = tau * do_ema lerps either way,
  no control flow.
- The alpha-gradient all_reduce of the reference (sac.py:72) is implicit:
  the minibatch is sharded over the mesh `data` axis, so XLA psums every
  gradient, including log_alpha's.
"""

from __future__ import annotations

import copy
import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.agent import SACAgent, build_agent
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.config.instantiate import instantiate, locate
from sheeprl_tpu.core.interact import InteractionPipeline
from sheeprl_tpu.core.resilience import watch
from sheeprl_tpu.core import mesh as mesh_lib
from sheeprl_tpu.core.mesh import DATA_AXIS
from sheeprl_tpu.core.player import PlayerPlacement
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import DeviceReplayRing
from sheeprl_tpu.core.runtime import DispatchThrottle
from sheeprl_tpu.registry import register_algorithm
from sheeprl_tpu.telemetry.health import health_probe, probes_enabled
from sheeprl_tpu.utils.checkpoint import load_checkpoint, restore_opt_state, save_checkpoint
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


def _make_optimizer(optim_cfg: Dict[str, Any]) -> optax.GradientTransformation:
    optim_cfg = dict(optim_cfg)
    target = optim_cfg.pop("_target_")
    return locate(target)(**optim_cfg)


def make_gradient_step(agent: SACAgent, txs: Dict[str, optax.GradientTransformation], cfg: Dict[str, Any]):
    """Build the pure one-minibatch update ``gradient_step(carry, batch,
    tau_eff)`` shared by the host-batched and ring-sampled train steps."""
    gamma = float(cfg.algo.gamma)

    def gradient_step(carry, batch, tau_eff):
        state, opt_states = carry
        k1, k2 = jax.random.split(batch.pop("_key"))

        # --- critic update (reference: sac.py:45-53)
        next_target = agent.next_target_q_values(
            state, batch["next_observations"], batch["rewards"], batch["terminated"], gamma, k1
        )

        def qf_loss_fn(qf_params):
            qf_values = agent.q_values(qf_params, batch["observations"], batch["actions"])
            return critic_loss(qf_values, next_target, agent.num_critics)

        qf_l, qf_grads = jax.value_and_grad(qf_loss_fn)(state["qfs"])
        qf_updates, qf_opt = txs["qf"].update(qf_grads, opt_states["qf"], state["qfs"])
        state["qfs"] = optax.apply_updates(state["qfs"], qf_updates)

        # --- target EMA (reference: sac.py:56-57)
        state["qfs_target"] = agent.target_ema(state["qfs"], state["qfs_target"], tau_eff)

        # --- actor update (reference: sac.py:59-66)
        alpha = jnp.exp(state["log_alpha"])

        def actor_loss_fn(actor_params):
            actions, logprobs = agent.actions_and_log_probs(actor_params, batch["observations"], k2)
            qf_values = agent.q_values(state["qfs"], batch["observations"], actions)
            min_qf = jnp.min(qf_values, axis=-1, keepdims=True)
            return policy_loss(alpha, logprobs, min_qf), logprobs

        (actor_l, logprobs), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(state["actor"])
        actor_updates, actor_opt = txs["actor"].update(actor_grads, opt_states["actor"], state["actor"])
        state["actor"] = optax.apply_updates(state["actor"], actor_updates)

        # --- alpha update (reference: sac.py:68-74)
        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, logprobs, agent.target_entropy)

        alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(state["log_alpha"])
        alpha_updates, alpha_opt = txs["alpha"].update(alpha_grads, opt_states["alpha"], state["log_alpha"])
        state["log_alpha"] = optax.apply_updates(state["log_alpha"], alpha_updates)

        opt_states = {"qf": qf_opt, "actor": actor_opt, "alpha": alpha_opt}
        metrics = {"value_loss": qf_l, "policy_loss": actor_l, "alpha_loss": alpha_l}
        if probes_enabled(cfg):
            # In-jit health probe: pure reductions over the already-live grad
            # and update trees — the scalars ride the StepTimer's coalesced
            # per-interval transfer, zero extra host syncs.
            metrics.update(
                health_probe(
                    params=(state["qfs"], state["actor"], state["log_alpha"]),
                    grads=(qf_grads, actor_grads, alpha_grads),
                    updates=(qf_updates, actor_updates, alpha_updates),
                    aux={"alpha": alpha, "entropy": -jnp.mean(logprobs)},
                )
            )
        return (state, opt_states), metrics

    return gradient_step


def partition_specs(mesh) -> mesh_lib.PartitionPlan:
    """SAC's partition-spec hook: scanned host minibatches are ``[G, B, ...]``
    (batch dim 1 over `data`), ring-sampled batches are flat ``[B, ...]``;
    params follow the default wide-param model-sharding rule."""
    from jax.sharding import PartitionSpec as P

    return mesh_lib.default_partition_plan(
        mesh,
        batch_specs={"scan_batch": P(None, DATA_AXIS), "batch": P(DATA_AXIS)},
    )


def _explicit_shardings(plan, state, opt_states, data_sharding):
    """jit ``in_shardings``/``out_shardings`` for the (state, opt_states,
    data, key, tau/taus) train-step signature, derived from the *placed*
    trees so the compiled layout matches the placement byte for byte.
    Gradient sync then lowers to XLA-inserted collectives over `data`
    instead of relying on implicit layout propagation. ``data_sharding``
    covers the third arg — a batch sharding prefix, a ring-state sharding
    tree, or None (unconstrained)."""
    state_sh = mesh_lib.tree_shardings(state)
    opt_sh = mesh_lib.tree_shardings(opt_states)
    repl = plan.replicated()
    return dict(
        in_shardings=(state_sh, opt_sh, data_sharding, repl, repl),
        out_shardings=(state_sh, opt_sh, None, repl),
    )


def make_train_step(
    agent: SACAgent,
    txs: Dict[str, optax.GradientTransformation],
    cfg: Dict[str, Any],
    mesh,
    state=None,
    opt_states=None,
):
    """Build the jitted G-gradient-steps update. With the placed ``state`` /
    ``opt_states`` trees given, the jit compiles with explicit
    ``in_shardings``/``out_shardings`` over the mesh (data-sharded batch +
    the params' own committed layouts)."""
    gradient_step = make_gradient_step(agent, txs, cfg)
    plan = partition_specs(mesh)
    batch_sharding = plan.sharding("scan_batch")

    jit_kwargs = {}
    divisible = int(cfg.algo.per_rank_batch_size) % plan.data_size == 0
    if state is not None and opt_states is not None and divisible:
        jit_kwargs = _explicit_shardings(plan, state, opt_states, batch_sharding)

    @partial(jax.jit, donate_argnums=(0, 1), **jit_kwargs)
    def train_step(state, opt_states, data, key, tau_eff):
        """data: dict of [G, B, ...] minibatches; tau_eff: tau or 0.
        Returns the split-off next key so the caller never runs an eager
        (host-blocking) split between calls — the key stays device-resident."""
        next_key, key = jax.random.split(key)
        data = jax.lax.with_sharding_constraint(data, {k: batch_sharding for k in data})
        keys = jax.random.split(key, data["rewards"].shape[0])
        data = dict(data, _key=keys)
        (state, opt_states), metrics = jax.lax.scan(
            lambda carry, batch: gradient_step(carry, batch, tau_eff), (state, opt_states), data
        )
        metrics = jax.tree_util.tree_map(lambda m: m.mean(0), metrics)
        return state, opt_states, metrics, next_key

    return train_step


def make_fused_train_step(
    agent: SACAgent,
    txs: Dict[str, optax.GradientTransformation],
    cfg: Dict[str, Any],
    mesh,
    sample_fn,
    state=None,
    opt_states=None,
    ring_shardings=None,
):
    """Build the ring-sampled K-step update: each scan iteration draws its
    minibatch from the device-resident replay ring with the JAX PRNG, so the
    host samples nothing and ships no batch bytes. K rides on ``taus``'s
    length (one EMA coefficient per step — the host fills them all with the
    iteration's tau_eff), so each power-of-two bucket compiles once.

    With the placed ``state``/``opt_states`` given, the jit compiles with
    explicit ``in_shardings``/``out_shardings``; ``ring_shardings`` (from
    :meth:`DeviceReplayRing.state_shardings`) pins the carried ring layout
    so a `data`-sharded ring stays sharded across supersteps."""
    gradient_step = make_gradient_step(agent, txs, cfg)
    plan = partition_specs(mesh)
    flat_sharding = plan.sharding("batch")

    jit_kwargs = {}
    divisible = int(cfg.algo.per_rank_batch_size) % plan.data_size == 0
    if state is not None and opt_states is not None and divisible:
        jit_kwargs = _explicit_shardings(plan, state, opt_states, ring_shardings)

    @partial(jax.jit, donate_argnums=(0, 1), **jit_kwargs)
    def fused_train_step(state, opt_states, ring_state, key, taus):
        next_key, key = jax.random.split(key)
        step_keys = jax.random.split(key, taus.shape[0])

        def body(carry, x):
            k, tau_eff = x
            k_sample, k_step = jax.random.split(k)
            batch = sample_fn(ring_state, k_sample)
            batch = jax.lax.with_sharding_constraint(batch, {name: flat_sharding for name in batch})
            batch = dict(batch, _key=k_step)
            return gradient_step(carry, batch, tau_eff)

        (state, opt_states), metrics = jax.lax.scan(body, (state, opt_states), (step_keys, taus))
        metrics = jax.tree_util.tree_map(lambda m: m.mean(0), metrics)
        return state, opt_states, metrics, next_key

    return fused_train_step


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    from sheeprl_tpu.core.fused_loop import fused_enabled, sac_fused_main

    if fused_enabled(cfg):
        # Anakin lane: pure-JAX env, rollout AND train inside one jit
        # (core/fused_loop.py). The host-interaction path below is untouched.
        return sac_fused_main(runtime, cfg)

    mesh = runtime.mesh
    rank = runtime.global_rank
    world_size = jax.process_count()

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir}")
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    watchdog = runtime.resilience.watchdog
    health = runtime.health

    envs = make_vector_env(cfg, rank, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}. "
                f"Provided environment: {cfg.env.id}"
            )
    if cfg.metric.log_level > 0:
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    # Eager flax/optax init runs host-side (each eager dispatch pays the
    # device-link round trip); the finished trees then move to the mesh.
    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime, cfg, observation_space, action_space,
            state_ckpt["agent"] if state_ckpt is not None else None,
        )

        txs = {
            "qf": _make_optimizer(cfg.algo.critic.optimizer),
            "actor": _make_optimizer(cfg.algo.actor.optimizer),
            "alpha": _make_optimizer(cfg.algo.alpha.optimizer),
        }
        opt_states = {
            "qf": txs["qf"].init(agent_state["qfs"]),
            "actor": txs["actor"].init(agent_state["actor"]),
            "alpha": txs["alpha"].init(agent_state["log_alpha"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (("qf", "qf_optimizer"), ("actor", "actor_optimizer"), ("alpha", "alpha_optimizer")):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])
    agent_state = runtime.shard_params(agent_state)
    opt_states = runtime.shard_params(opt_states)
    # Arm per-shard goodput accounting and record the topology + param
    # layouts for the `telemetry mesh` inspector, now that both exist.
    telemetry.set_mesh(mesh)
    telemetry.record_param_layouts(agent_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(cfg.env.num_envs * world_size) if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        cfg.env.num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
    )
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        rb = state_ckpt["rb"]

    last_train = 0
    train_step_count = 0
    start_iter = (state_ckpt["iter_num"] // world_size) + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * cfg.env.num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    def _player(p, o, k):
        # PRNG split in-graph: the jitted call is the step's only dispatch.
        next_k, sub = jax.random.split(k)
        return agent.get_actions(p, o, sub, greedy=False), next_k

    player_fn = jax.jit(_player)
    train_fn = make_train_step(agent, txs, cfg, mesh, state=agent_state, opt_states=opt_states)
    target_freq_iters = cfg.algo.critic.target_network_frequency // policy_steps_per_iter + 1

    # Device-resident replay ring (data/device_buffer.py): transitions are
    # mirrored into HBM and sampled inside the fused train jit — the host
    # sample + [G*B] batch transfer above drop out of the hot path. Falls
    # back to the host buffer when the ring won't fit the HBM budget.
    use_device_buffer = bool(cfg.buffer.get("device", False))
    fused_train_steps = max(int(cfg.algo.get("fused_train_steps", 1)), 1)
    ring = None
    fused_train_fn = None
    ring_span = 1 + int(bool(cfg.buffer.sample_next_obs))
    if use_device_buffer:
        ring = DeviceReplayRing(
            buffer_size,
            cfg.env.num_envs,
            obs_keys=("observations",),
            hbm_fraction=float(cfg.buffer.get("device_hbm_fraction", 0.4)),
            device=mesh.devices.flat[0],
            mesh=mesh,
        )
        if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
            ring.load_host_buffer(rb)
        ring_sample_fn = ring.make_sample_fn(
            cfg.algo.per_rank_batch_size,
            sequence_length=1,
            sample_next_obs=bool(cfg.buffer.sample_next_obs),
        )
        fused_train_fn = make_fused_train_step(
            agent, txs, cfg, mesh, ring_sample_fn,
            state=agent_state, opt_states=opt_states, ring_shardings=ring.state_shardings(),
        )

    # Latency-aware player placement (core/player.py). Off-policy: honors
    # fabric.player_sync=async (the player may act on weights one update
    # stale, never blocking the interaction loop on the mirror transfer).
    placement = PlayerPlacement.resolve(cfg, mesh.devices.flat[0], params=agent_state["actor"])
    placement.push(agent_state["actor"])

    rollout_key, train_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key = placement.put(rollout_key)

    # Pipelined interaction (core/interact.py): per-slice policy dispatch +
    # async action fetch + double-buffered obs staging. slices=1/async off is
    # bit-identical to the serial loop.
    pipeline = InteractionPipeline.from_config(cfg)
    pipeline.watchdog = watchdog
    pipeline.set_key(rollout_key)
    single_action_shape = envs.single_action_space.shape

    def _pipeline_policy(np_obs, state, key):
        with placement.ctx():
            actions_j, next_key = player_fn(placement.params(), np_obs, key)
        return actions_j, state, next_key

    def _prepare_slice(obs_slice, out=None):
        n = len(next(iter(obs_slice.values())))
        return prepare_obs(obs_slice, mlp_keys=mlp_keys, num_envs=n, out=out)

    def _to_env_actions(host_actions, n_envs):
        return host_actions.reshape((n_envs, *single_action_shape))

    step_data = {}
    obs = pipeline.stash_obs(envs.reset(seed=cfg.seed)[0])

    cumulative_per_rank_gradient_steps = 0
    # Bound async in-flight train dispatches (core/runtime.py: an
    # unbounded queue pins every pending call's sampled batch on host).
    dispatch_throttle = DispatchThrottle()
    # Train losses stay device-resident between log intervals; the StepTimer
    # coalesces them into ONE jax.device_get per interval and bounds the
    # interval's wall-clock with ONE block_until_ready (each sync is a full
    # round trip over a tunneled chip). Scalars only, so the pinned device
    # memory is negligible.
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    perf = telemetry.perf
    keep_train_metrics = (
        aggregator is not None and not aggregator.disabled and cfg.metric.log_level > 0
    ) or health.enabled

    # The iteration's gradient steps, factored out so the pipelined
    # interaction can dispatch them between the action-fetch submit and its
    # harvest (pipeline.overlap_train): train compute then overlaps the D2H
    # copy and the host env step, at the cost of train batches lagging the
    # buffer by one transition.
    def run_train(iter_num: int) -> None:
        nonlocal agent_state, opt_states, train_key, train_step_count, cumulative_per_rank_gradient_steps
        if iter_num < learning_starts:
            return
        per_rank_gradient_steps = ratio((policy_step - prefill_steps + policy_steps_per_iter) / world_size)
        if per_rank_gradient_steps > 0:
            if ring is not None and ring.active:
                ring.flush()
            use_ring = ring is not None and ring.active and ring.ready(ring_span)
            if use_ring:
                with timer("Time/train_time"):
                    do_ema = iter_num % target_freq_iters == 0
                    tau_eff = np.float32(agent.tau if do_ema else 0.0)
                    remaining = per_rank_gradient_steps
                    while remaining > 0:
                        # Power-of-two buckets bound the fused graphs to
                        # log2(fused_train_steps) variants.
                        k = 1 << (min(remaining, fused_train_steps).bit_length() - 1)
                        taus = np.full(k, tau_eff, np.float32)
                        # Goodput accounting BEFORE the dispatch: arg shape
                        # specs must be captured while the buffers are alive
                        # (the jit donates them).
                        perf.note(
                            f"train/fused_k{k}", fused_train_fn,
                            (agent_state, opt_states, ring.state, train_key, taus), steps=k,
                        )
                        with train_timer.step(), watch(watchdog, "train_dispatch"):
                            agent_state, opt_states, train_metrics, train_key = fused_train_fn(
                                agent_state, opt_states, ring.state, train_key, taus,
                            )
                        train_timer.pend(
                            agent_state["actor"], train_metrics if keep_train_metrics else None
                        )
                        dispatch_throttle.add(train_metrics)
                        cumulative_per_rank_gradient_steps += k
                        remaining -= k
                    placement.push(agent_state["actor"])
                train_step_count += world_size
            else:
                sample = rb.sample_tensors(
                    batch_size=per_rank_gradient_steps * cfg.algo.per_rank_batch_size,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )
                data = {
                    k: np.asarray(v)
                    .astype(np.float32)
                    .reshape(per_rank_gradient_steps, cfg.algo.per_rank_batch_size, *np.asarray(v).shape[2:])
                    for k, v in sample.items()
                }
                with timer("Time/train_time"):
                    do_ema = iter_num % target_freq_iters == 0
                    # tau as numpy (an eager jnp.asarray would dispatch);
                    # the PRNG split happens inside the jit.
                    tau_arr = np.asarray(agent.tau if do_ema else 0.0, np.float32)
                    perf.note(
                        f"train/g{per_rank_gradient_steps}", train_fn,
                        (agent_state, opt_states, data, train_key, tau_arr),
                        steps=per_rank_gradient_steps,
                    )
                    with train_timer.step(), watch(watchdog, "train_dispatch"):
                        agent_state, opt_states, train_metrics, train_key = train_fn(
                            agent_state,
                            opt_states,
                            data,
                            train_key,
                            tau_arr,
                        )
                    # No sync here: the dispatch stays fully async — the
                    # StepTimer queues the loss scalars device-side and
                    # bounds the interval with ONE block at the flush below.
                    train_timer.pend(
                        agent_state["actor"], train_metrics if keep_train_metrics else None
                    )
                    dispatch_throttle.add(train_metrics)
                    placement.push(agent_state["actor"])
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                train_step_count += world_size

    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter
        telemetry.advance(policy_step)
        guard.advance(policy_step)

        trained_in_flight = False
        with timer("Time/env_interaction_time"), perf.infeed():
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
                next_obs, rewards, terminated, truncated, infos = envs.step(
                    actions.reshape(envs.action_space.shape)
                )
                next_obs = pipeline.stash_obs(next_obs)
            else:
                # Overlap the train dispatch with the action copy + env step
                # only once the buffer has at least one post-prefill
                # transition (at the very first train the buffer would
                # otherwise be one step short).
                trained_in_flight = pipeline.overlap_train and iter_num > learning_starts + 1
                res = pipeline.interact(
                    envs,
                    obs,
                    _pipeline_policy,
                    prepare=_prepare_slice,
                    to_env_actions=_to_env_actions,
                    before_harvest=(lambda: run_train(iter_num)) if trained_in_flight else None,
                )
                actions, next_obs, rewards, terminated, truncated, infos = (
                    res.outputs,
                    res.obs,
                    res.rewards,
                    res.terminated,
                    res.truncated,
                    res.infos,
                )
            rewards = rewards.reshape(cfg.env.num_envs, -1)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            for i in np.nonzero(fi.get("_episode", []))[0]:
                ep_rew = float(fi["episode"]["r"][i])
                ep_len = float(fi["episode"]["l"][i])
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        # Real next obs for the buffer: replace autoreset obs with final_obs
        # (reference: sac.py:276-284).
        real_next_obs = copy.deepcopy(next_obs)
        if "final_obs" in infos:
            done_mask = np.logical_or(terminated, truncated)
            for idx in np.nonzero(done_mask)[0]:
                final = infos["final_obs"][idx]
                if final is not None:
                    for k, v in final.items():
                        real_next_obs[k][idx] = v
        real_next_obs_cat = np.concatenate([real_next_obs[k] for k in mlp_keys], axis=-1).astype(np.float32)

        step_data["terminated"] = terminated.reshape(1, cfg.env.num_envs, -1).astype(np.uint8)
        step_data["truncated"] = truncated.reshape(1, cfg.env.num_envs, -1).astype(np.uint8)
        step_data["actions"] = actions.reshape(1, cfg.env.num_envs, -1)
        step_data["observations"] = np.concatenate([obs[k] for k in mlp_keys], axis=-1).astype(np.float32)[np.newaxis]
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = real_next_obs_cat[np.newaxis]
        step_data["rewards"] = rewards[np.newaxis].astype(np.float32)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        if ring is not None:
            ring.add(step_data)

        obs = next_obs

        if not trained_in_flight:
            run_train(iter_num)

        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            # The interval's ONE bounding block + ONE coalesced device->host
            # transfer of every queued loss tree (StepTimer.flush) — the
            # pattern GL002 asks for, now owned by telemetry.
            fetched_train_metrics = train_timer.flush()
            # Health sentinels inspect the same coalesced fetch — no extra
            # transfer. A nonfinite hit taints the run (vetoing further
            # checkpoint saves) and escalates per cfg.health.policy.
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            if aggregator and not aggregator.disabled:
                for tm in fetched_train_metrics:
                    aggregator.update("Loss/value_loss", tm["value_loss"])
                    aggregator.update("Loss/policy_loss", tm["policy_loss"])
                    aggregator.update("Loss/alpha_loss", tm["alpha_loss"])
                # Collective when sync_on_compute is on: every rank joins;
                # only rank 0 (the only rank with a logger) writes.
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            logger.log(
                "Params/replay_ratio", cumulative_per_rank_gradient_steps * world_size / policy_step, policy_step
            )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log(
                        "Time/sps_env_interaction",
                        ((policy_step - last_log) / world_size * cfg.env.action_repeat)
                        / timer_metrics["Time/env_interaction_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": agent_state,
                "qf_optimizer": opt_states["qf"],
                "actor_optimizer": opt_states["actor"],
                "alpha_optimizer": opt_states["alpha"],
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            saved_tail = None
            tail = (rb._pos - 1) % rb.buffer_size
            if cfg.buffer.checkpoint:
                # Buffer-tail consistency trick: mark the episode open at the
                # write head truncated inside the snapshot, then restore
                # (reference: callback.py:87-142).
                if rb["truncated"] is not None:
                    saved_tail = np.asarray(rb["truncated"][tail, :]).copy()
                    rb["truncated"][tail, :] = 1
                ckpt_state["rb"] = rb
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)
            if saved_tail is not None:
                rb["truncated"][tail, :] = saved_tail

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break
    pipeline.publish()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, agent_state, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
