"""SAC policy adapter for the serving subsystem.

Export keeps the actor params only — critics, targets, and the temperature
are training-time state. The apply path is exactly the evaluate path
(`sac/utils.py test()`): concatenated mlp-key vector obs through
``SACAgent.get_actions``, so a single-request greedy batch is bit-identical
to ``evaluate_sac``.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Tuple

import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActorModule, SACAgent
from sheeprl_tpu.serve.adapter import PolicyAdapterBase, extract_policy_config, seeds_to_keys
from sheeprl_tpu.serve.registry import register_policy


@register_policy(["sac", "sac_decoupled"])
class SACPolicy(PolicyAdapterBase):
    stateful = False

    # ------------------------------------------------------------ export side
    @classmethod
    def export(cls, state: Dict[str, Any], cfg) -> Tuple[Any, Dict[str, Any]]:
        return {"actor": state["agent"]["actor"]}, extract_policy_config(cfg)

    # -------------------------------------------------------------- load side
    def __init__(self, spec: Dict[str, Any], params: Any) -> None:
        super().__init__(spec, params)
        act_dim = int(prod(self.action_space.shape))
        actor = SACActorModule(
            action_dim=act_dim,
            hidden_size=self.cfg.algo.actor.hidden_size,
            dtype=self.compute_dtype,
        )
        # Only the actor half of the agent exists at inference: critics and
        # temperature are deliberately absent from the artifact.
        self.agent = SACAgent(
            actor=actor,
            critics=None,
            action_scale=np.asarray((self.action_space.high - self.action_space.low) / 2.0, np.float32),
            action_bias=np.asarray((self.action_space.high + self.action_space.low) / 2.0, np.float32),
            target_entropy=float(-act_dim),
            tau=0.0,
            num_critics=0,
        )

    def pack_rows(self, rows: List[Dict[str, np.ndarray]], batch: int) -> np.ndarray:
        # prepare_obs parity: mlp keys concatenated into one float32 [B, D].
        layout = self.row_spec()
        width = sum(int(prod(shape)) for shape, _ in layout.values())
        out = np.zeros((batch, width), np.float32)
        for i, row in enumerate(rows):
            out[i] = np.concatenate([row[k].ravel() for k in self.mlp_keys])
        return out

    def make_apply(self, greedy: bool):
        import jax

        agent = self.agent
        if greedy:

            def apply(params, obs, seeds, state):
                return agent.get_actions(params["actor"], obs, greedy=True), state

            return apply

        def apply(params, obs, seeds, state):
            keys = seeds_to_keys(seeds)

            def row(o, k):
                return agent.get_actions(params["actor"], o[None], key=k)[0]

            return jax.vmap(row)(obs, keys), state

        return apply
