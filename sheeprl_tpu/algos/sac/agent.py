"""SAC agent (flax): squashed-Gaussian actor + vmapped twin-Q ensemble.

Capability parity with the reference agent (sheeprl/algos/sac/agent.py:20-371),
restructured for TPU:
- The reference keeps a python list of critic modules and concatenates their
  outputs (agent.py:248-253). Here the ensemble is ONE module vmapped over a
  leading `n_critics` parameter axis (`nn.vmap`), so all critics run as a
  single batched matmul — MXU-friendly, no per-critic dispatch.
- Target critics are a params COPY in the train state (EMA by tree_map lerp,
  reference qfs_target_ema at agent.py:264-267), not modules.
- The player/trainer weight tying of the reference (agent.py:368-370) is
  structural: the same actor params serve both paths.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import prod
from typing import Any, Dict, Optional, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.models import MLP

LOG_STD_MIN = -5
LOG_STD_MAX = 2


class SACActorModule(nn.Module):
    """2-layer MLP trunk → (mean, log_std) heads
    (reference: SACActor, agent.py:57-142)."""

    action_dim: int
    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            activation="relu",
            dtype=self.dtype,
            name="model",
        )(obs)
        mean = nn.Dense(self.action_dim, dtype=self.dtype, name="fc_mean")(x)
        log_std = nn.Dense(self.action_dim, dtype=self.dtype, name="fc_logstd")(x)
        return mean, log_std


class SACCriticModule(nn.Module):
    """Q(obs, act) MLP (reference: SACCritic, agent.py:20-54)."""

    hidden_size: int = 256
    num_critics: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=self.num_critics,
            activation="relu",
            dtype=self.dtype,
            name="model",
        )(x)


class SACCriticEnsemble(nn.Module):
    """N independent critics as one vmapped module: params gain a leading
    [n] axis, outputs stack to [B, n]."""

    n: int
    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            SACCriticModule,
            in_axes=None,
            out_axes=-1,
            axis_size=self.n,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(hidden_size=self.hidden_size, num_critics=1, dtype=self.dtype, name="qfs")
        return ensemble(obs, action)[..., 0, :]  # [B, 1, n] → [B, n]


def squash_and_logprob(
    mean: jax.Array,
    log_std: jax.Array,
    key: jax.Array,
    action_scale: jax.Array,
    action_bias: jax.Array,
    log_std_clip: Optional[Tuple[float, float]] = (LOG_STD_MIN, LOG_STD_MAX),
) -> Tuple[jax.Array, jax.Array]:
    """Reparameterized tanh-squashed sample, rescaled to env bounds, with the
    eq. 26 log-prob correction (reference: agent.py:110-142). Pass
    ``log_std_clip=None`` when the actor already bounds log_std (SAC-AE)."""
    if log_std_clip is not None:
        log_std = jnp.clip(log_std, *log_std_clip)
    std = jnp.exp(log_std)
    x_t = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
    y_t = jnp.tanh(x_t)
    action = y_t * action_scale + action_bias
    log_prob = -((x_t - mean) ** 2) / (2 * std**2) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)
    log_prob = log_prob - jnp.log(action_scale * (1 - y_t**2) + 1e-6)
    return action, log_prob.sum(-1, keepdims=True)


@dataclass(frozen=True)
class SACAgent:
    """Bundles modules + action-space metadata; params/targets live in the
    train state dict: {actor, qfs, qfs_target, log_alpha}."""

    actor: SACActorModule
    critics: SACCriticEnsemble
    action_scale: np.ndarray
    action_bias: np.ndarray
    target_entropy: float
    tau: float
    num_critics: int

    def actions_and_log_probs(self, actor_params, obs: jax.Array, key: jax.Array):
        mean, log_std = self.actor.apply(actor_params, obs)
        return squash_and_logprob(
            mean, log_std, key, jnp.asarray(self.action_scale), jnp.asarray(self.action_bias)
        )

    def q_values(self, qf_params, obs: jax.Array, action: jax.Array) -> jax.Array:
        return self.critics.apply(qf_params, obs, action)

    def next_target_q_values(
        self, state: Dict[str, Any], next_obs, rewards, terminated, gamma: float, key: jax.Array
    ) -> jax.Array:
        """Soft Bellman target (reference: get_next_target_q_values,
        agent.py:256-262)."""
        next_actions, next_log_pi = self.actions_and_log_probs(state["actor"], next_obs, key)
        qf_next = self.q_values(state["qfs_target"], next_obs, next_actions)
        alpha = jnp.exp(state["log_alpha"])
        min_qf_next = jnp.min(qf_next, axis=-1, keepdims=True) - alpha * next_log_pi
        return rewards + (1 - terminated) * gamma * min_qf_next

    def target_ema(self, qf_params, qf_target_params, tau: Optional[jax.Array] = None):
        """Polyak update (reference: qfs_target_ema, agent.py:264-267)."""
        t = self.tau if tau is None else tau
        return jax.tree_util.tree_map(lambda p, tp: t * p + (1 - t) * tp, qf_params, qf_target_params)

    def get_actions(self, actor_params, obs: jax.Array, key: Optional[jax.Array] = None, greedy: bool = False):
        """Env-facing actions (reference: SACPlayer, agent.py:288-314)."""
        mean, log_std = self.actor.apply(actor_params, obs)
        scale = jnp.asarray(self.action_scale)
        bias = jnp.asarray(self.action_bias)
        if greedy:
            return jnp.tanh(mean) * scale + bias
        std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
        x_t = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
        return jnp.tanh(x_t) * scale + bias


def build_agent(
    runtime,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACAgent, Dict[str, Any]]:
    """Construct modules + initial (or restored) train state
    (reference: build_agent, agent.py:317-371)."""
    act_dim = int(prod(action_space.shape))
    obs_dim = int(sum(prod(obs_space[k].shape) for k in cfg.algo.mlp_keys.encoder))
    dtype = runtime.precision.compute_dtype
    actor = SACActorModule(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size, dtype=dtype)
    critics = SACCriticEnsemble(n=cfg.algo.critic.n, hidden_size=cfg.algo.critic.hidden_size, dtype=dtype)
    agent = SACAgent(
        actor=actor,
        critics=critics,
        action_scale=np.asarray((action_space.high - action_space.low) / 2.0, np.float32),
        action_bias=np.asarray((action_space.high + action_space.low) / 2.0, np.float32),
        target_entropy=float(-act_dim),
        tau=float(cfg.algo.tau),
        num_critics=int(cfg.algo.critic.n),
    )
    if agent_state is not None:
        state = jax.tree_util.tree_map(jnp.asarray, agent_state)
    else:
        k_actor, k_qfs = jax.random.split(runtime.root_key)
        dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
        dummy_act = jnp.zeros((1, act_dim), jnp.float32)
        actor_params = actor.init(k_actor, dummy_obs)
        qf_params = critics.init(k_qfs, dummy_obs, dummy_act)
        state = {
            "actor": actor_params,
            "qfs": qf_params,
            "qfs_target": jax.tree_util.tree_map(jnp.copy, qf_params),
            "log_alpha": jnp.log(jnp.asarray([float(cfg.algo.alpha.alpha)], jnp.float32)),
        }
    return agent, state
