"""SAC actor replica — the fleet (multi-process Sebulba) twin of the env
interaction block in ``sac_decoupled.main``.

Runs inside a :class:`~sheeprl_tpu.core.fleet.FleetSupervisor` replica
process: step the vector env, ship one rows message per vector step (the
shipment doubles as the heartbeat), act randomly until the learner's first
params broadcast arrives (the process-level analog of the learning-starts
prefill), then with the newest actor snapshot thereafter. Off-policy SAC
makes the replica embarrassingly restartable: transitions are self-contained,
so the learner interleaves shipments from any mix of replica generations and
a restarted replica simply starts shipping fresh trajectories from its
``SeedSequence([seed, replica, restart])`` reseed.
"""

from __future__ import annotations

import copy

import numpy as np


class _ActorRuntime:
    """The two attributes ``build_agent`` reads from the real Runtime —
    constructing the full Runtime in a replica would launch meshes and
    telemetry the actor has no use for."""

    def __init__(self, cfg, seed: int) -> None:
        import jax

        from sheeprl_tpu.core.precision import resolve_precision

        self.precision = resolve_precision(str(cfg.fabric.get("precision", "32-true") or "32-true"))
        self.root_key = jax.random.PRNGKey(int(seed))


def actor_loop(ctx) -> None:
    """Fleet replica entry (``sheeprl_tpu.algos.sac.fleet_actor:actor_loop``)."""
    import jax

    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.utils import prepare_obs
    from sheeprl_tpu.utils.env import make_vector_env

    cfg = ctx.cfg
    # The replica's whole stochastic world (env seeds, action sampling, agent
    # init) keys off the supervisor-derived seed: restart k explores fresh
    # trajectories, deterministically.
    cfg.seed = ctx.seed
    num_envs = int(cfg.env.num_envs)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    sample_next_obs = bool(cfg.buffer.sample_next_obs)

    envs = make_vector_env(cfg, ctx.replica, None)
    agent, _ = build_agent(
        _ActorRuntime(cfg, ctx.seed), cfg, envs.single_observation_space, envs.single_action_space
    )

    def _player(p, o, k):
        next_k, sub = jax.random.split(k)
        return agent.get_actions(p, o, sub, greedy=False), next_k

    player_fn = jax.jit(_player)
    key = jax.random.PRNGKey(ctx.seed)

    obs = envs.reset(seed=cfg.seed)[0]
    actor_params = None
    row = {}
    try:
        while not ctx.should_stop():
            got = ctx.poll_params()
            if got is not None:
                actor_params = got[1]
            if actor_params is None:
                # No broadcast yet: the learner is still prefilling — random
                # actions, exactly like the in-process loop before
                # learning_starts.
                actions = envs.action_space.sample()
            else:
                np_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)
                actions_j, key = player_fn(actor_params, np_obs, key)
                actions = np.asarray(actions_j)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
            rewards = rewards.reshape(num_envs, -1)

            episodes = []
            if "final_info" in infos:
                fi = infos["final_info"]
                for i in np.nonzero(fi.get("_episode", []))[0]:
                    episodes.append((float(fi["episode"]["r"][i]), float(fi["episode"]["l"][i])))

            real_next_obs = copy.deepcopy(next_obs)
            if "final_obs" in infos:
                done_mask = np.logical_or(terminated, truncated)
                for idx in np.nonzero(done_mask)[0]:
                    final = infos["final_obs"][idx]
                    if final is not None:
                        for k2, v in final.items():
                            real_next_obs[k2][idx] = v

            row["terminated"] = terminated.reshape(1, num_envs, -1).astype(np.uint8)
            row["truncated"] = truncated.reshape(1, num_envs, -1).astype(np.uint8)
            row["actions"] = actions.reshape(1, num_envs, -1)
            row["observations"] = np.concatenate(
                [obs[k] for k in mlp_keys], axis=-1
            ).astype(np.float32)[np.newaxis]
            if not sample_next_obs:
                row["next_observations"] = np.concatenate(
                    [real_next_obs[k] for k in mlp_keys], axis=-1
                ).astype(np.float32)[np.newaxis]
            row["rewards"] = rewards[np.newaxis].astype(np.float32)

            # Ship-or-drop: a drop_shipment injector swallows the send; the
            # env steps still happened, which is exactly the gap the
            # learner-side accounting and idle pings must absorb.
            ctx.ship(row, env_steps=num_envs, episodes=episodes)
            obs = next_obs
    finally:
        envs.close()
