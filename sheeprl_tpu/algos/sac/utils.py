"""SAC auxiliary contract (reference: sheeprl/algos/sac/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.env import make_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    obs: Dict[str, np.ndarray],
    *,
    mlp_keys: Sequence[str] = (),
    num_envs: int = 1,
    out: np.ndarray = None,
    **kwargs: Any,
) -> np.ndarray:
    """Vector obs → single concatenated float32 numpy array [num_envs, D]
    (reference: utils.py:31-36). Numpy on purpose: eager jnp ops here would
    each be a device dispatch per env step. ``out`` is a preallocated
    [num_envs, D] staging buffer (core/interact.py ObsStager) written in
    place instead of allocating."""
    if out is not None:
        col = 0
        for k in mlp_keys:
            a = np.asarray(obs[k], np.float32).reshape(num_envs, -1)
            out[:, col : col + a.shape[1]] = a
            col += a.shape[1]
        return out
    return np.concatenate(
        [np.asarray(obs[k], np.float32) for k in mlp_keys], axis=-1
    ).reshape(num_envs, -1)


def test(agent, state, runtime, cfg: Dict[str, Any], log_dir: str, logger=None) -> float:
    """One greedy episode (reference: utils.py:39-61)."""
    env = make_env(cfg, None, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    get_actions = jax.jit(lambda p, o: agent.get_actions(p, o, greedy=True))
    while not done:
        jnp_obs = prepare_obs(obs, mlp_keys=cfg.algo.mlp_keys.encoder)
        action = np.asarray(get_actions(state["actor"], jnp_obs))
        obs, reward, done, truncated, _ = env.step(action.reshape(env.action_space.shape))
        done = done or truncated
        cumulative_rew += reward
        if cfg.dry_run:
            done = True
    runtime.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and logger is not None:
        logger.log_dict({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
    return cumulative_rew
