"""SAC evaluation entrypoint (reference: sheeprl/algos/sac/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.registry import register_evaluation
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger


@register_evaluation(algorithms=["sac", "sac_decoupled"])
def evaluate_sac(runtime, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir}")

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    action_space = env.action_space
    observation_space = env.observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)

    agent, agent_state = build_agent(runtime, cfg, observation_space, action_space, state["agent"])
    env.close()
    test(agent, agent_state, runtime, cfg, log_dir, logger)
