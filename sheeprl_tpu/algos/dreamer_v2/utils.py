"""DreamerV2 auxiliary contract (reference: sheeprl/algos/dreamer_v2/utils.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

# prepare_obs and the greedy test episode are identical to DreamerV3's (both
# players expose the same functional player_step API).
from sheeprl_tpu.algos.dreamer_v3.utils import normalize_player_obs, prepare_obs, test  # noqa: F401 (re-export)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/post_entropy",
    "State/prior_entropy",
    "State/kl",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    bootstrap: jax.Array = None,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV2-style TD(λ) over [H, ...] arrays with an explicit bootstrap
    (reference reverse loop: dreamer_v2/utils.py:85-101):
    L[t] = r[t] + c[t] * ((1 - λ) * V[t+1] + λ * L[t+1]), seeded with the
    bootstrap value. One reverse `lax.scan`, fp32 accumulation.
    """
    if bootstrap is None:
        bootstrap = jnp.zeros_like(values[-1:])
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    continues = continues.astype(jnp.float32)
    bootstrap = bootstrap.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def step(agg, x):
        i, c = x
        agg = i + c * lmbda * agg
        return agg, agg

    _, out = jax.lax.scan(step, bootstrap[0], (inputs, continues), reverse=True)
    return out
