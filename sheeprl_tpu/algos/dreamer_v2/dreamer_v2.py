"""DreamerV2 training loop (reference: sheeprl/algos/dreamer_v2/dreamer_v2.py).

TPU-first structure, same shape as the DreamerV3 loop in this package
(SURVEY §3.3 / §7.2): the RSSM runs as ONE `lax.scan` over the sequence axis,
imagination is a second scan over the horizon, λ-targets are a reverse scan,
and the whole gradient step (world model + actor + critic) is one jitted,
donated call. DV2-specific pieces: KL-balanced world-model loss
(loss.py), Normal(·,1) reward/critic/decoder heads, REINFORCE/dynamics-mixed
actor objective (reference dreamer_v2.py:307-331), a hard-copied target
critic every `per_rank_target_network_update_freq` gradient steps
(dreamer_v2.py:697-703, done host-side here), and an optional EpisodeBuffer
(`buffer.type=episode`, dreamer_v2.py:498-521).
"""

from __future__ import annotations

import copy
import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v2.agent import (
    DV2Agent,
    DV2WorldModel,
    build_agent,
    dv2_actor_forward,
)
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v2.utils import compute_lambda_values, normalize_player_obs, prepare_obs, test
from sheeprl_tpu.algos.ppo.agent import actions_metadata
from sheeprl_tpu.config.instantiate import instantiate, locate
from sheeprl_tpu.core.interact import InteractionPipeline
from sheeprl_tpu.core.mesh import DATA_AXIS
from sheeprl_tpu.core.player import PlayerPlacement
from sheeprl_tpu.data.infeed import ReplayInfeed
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, SequentialReplayBuffer
from sheeprl_tpu.core.runtime import DispatchThrottle
from sheeprl_tpu.registry import register_algorithm
from sheeprl_tpu.utils.checkpoint import load_checkpoint, restore_opt_state, save_checkpoint
from sheeprl_tpu.utils.distribution import BernoulliSafeMode, Independent, Normal, OneHotCategorical
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


def _make_optimizer(optim_cfg: Dict[str, Any], clip: float) -> optax.GradientTransformation:
    optim_cfg = dict(optim_cfg)
    target = optim_cfg.pop("_target_")
    inner = locate(target)(**optim_cfg)
    if clip is not None and clip > 0:
        return optax.chain(optax.clip_by_global_norm(clip), inner)
    return inner


def make_train_step(agent: DV2Agent, txs: Dict[str, optax.GradientTransformation], cfg: Dict[str, Any], mesh):
    """Build the jitted single-gradient-step function over a [T, B] batch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    stochastic_size = int(wm_cfg.stochastic_size)
    discrete_size = int(wm_cfg.discrete_size)
    stoch_state_size = stochastic_size * discrete_size
    recurrent_state_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    ent_coef = float(cfg.algo.actor.ent_coef)
    objective_mix = float(cfg.algo.actor.objective_mix)
    use_continues = bool(wm_cfg.use_continues)
    spec = agent.actor_spec
    actions_dim = agent.actions_dim

    batch_sharding = NamedSharding(mesh, P(None, DATA_AXIS))

    def world_loss_fn(wm_params, data, batch_obs, keys):
        T, B = data["rewards"].shape[:2]
        embedded = agent.wm(wm_params, batch_obs, method="embed_obs")

        is_first = data["is_first"].at[0].set(1.0)
        h0 = jnp.zeros((B, recurrent_state_size), embedded.dtype)
        z0 = jnp.zeros((B, stoch_state_size), embedded.dtype)

        def step(carry, x):
            h, z = carry
            action, emb, first, key = x
            h, post, prior, post_logits, prior_logits = agent.world_model.apply(
                wm_params, z, h, action, emb, first, key, method=DV2WorldModel.dynamic
            )
            return (h, post), (h, post, post_logits, prior_logits)

        (_, _), (recurrent_states, posteriors, posteriors_logits, priors_logits) = jax.lax.scan(
            step, (h0, z0), (data["actions"], embedded, is_first, keys)
        )
        latent_states = jnp.concatenate([posteriors, recurrent_states], -1)

        reconstructed_obs = agent.wm(wm_params, latent_states, method="decode")
        po = {
            k: Independent(Normal(v, jnp.ones_like(v)), len(v.shape[2:]))
            for k, v in reconstructed_obs.items()
        }
        pr = Independent(Normal(agent.wm(wm_params, latent_states, method="reward"), 1.0), 1)
        if use_continues:
            pc = Independent(
                BernoulliSafeMode(logits=agent.wm(wm_params, latent_states, method="continue_logits")), 1
            )
            continues_targets = (1 - data["terminated"]) * gamma
        else:
            pc = continues_targets = None

        pl = priors_logits.reshape(*priors_logits.shape[:-1], stochastic_size, discrete_size)
        pol = posteriors_logits.reshape(*posteriors_logits.shape[:-1], stochastic_size, discrete_size)
        rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
            po,
            batch_obs,
            pr,
            data["rewards"],
            pl,
            pol,
            wm_cfg.kl_balancing_alpha,
            wm_cfg.kl_free_nats,
            wm_cfg.kl_free_avg,
            wm_cfg.kl_regularizer,
            pc,
            continues_targets,
            wm_cfg.discount_scale_factor,
        )
        aux = {
            "posteriors": posteriors,
            "recurrent_states": recurrent_states,
            "posteriors_logits": pol,
            "priors_logits": pl,
            "kl": kl.mean(),
            "state_loss": state_loss,
            "reward_loss": reward_loss,
            "observation_loss": observation_loss,
            "continue_loss": continue_loss,
        }
        return rec_loss, aux

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(state, opt_states, data, key):
        next_key, key = jax.random.split(key)
        T, B = data["rewards"].shape[:2]
        data = jax.lax.with_sharding_constraint(data, {k: batch_sharding for k in data})
        batch_obs = {k: data[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k] for k in mlp_keys})

        k_dyn, k_img, k_actor = jax.random.split(key, 3)
        dyn_keys = jax.random.split(k_dyn, T)

        # ---------------------------------------------- world model update
        (rec_loss, aux), wm_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(
            state["world_model"], data, batch_obs, dyn_keys
        )
        wm_updates, wm_opt = txs["world_model"].update(
            wm_grads, opt_states["world_model"], state["world_model"]
        )
        state["world_model"] = optax.apply_updates(state["world_model"], wm_updates)

        # --------------------------------------------- behaviour learning
        sg = jax.lax.stop_gradient
        imagined_prior0 = sg(aux["posteriors"]).reshape(-1, stoch_state_size)
        recurrent_state0 = sg(aux["recurrent_states"]).reshape(-1, recurrent_state_size)
        latent0 = jnp.concatenate([imagined_prior0, recurrent_state0], -1)

        def actor_sample(actor_params, latent, k):
            pre = agent.actor.apply(actor_params, sg(latent))
            actions, _ = dv2_actor_forward(pre, spec, k, greedy=False)
            return jnp.concatenate(actions, -1)

        def imagine_loss_fn(actor_params):
            # Rollout: imagined_actions[0] is the zero action; action i is
            # taken FROM state i-1 (reference: dreamer_v2.py:239-259).
            def img_step(carry, k):
                prior, h, latent = carry
                k_act, k_wm = jax.random.split(k)
                actions = actor_sample(actor_params, latent, k_act)
                prior, h = agent.world_model.apply(
                    state["world_model"], prior, h, actions, k_wm, method=DV2WorldModel.imagination
                )
                latent = jnp.concatenate([prior, h], -1)
                return (prior, h, latent), (latent, actions)

            img_keys = jax.random.split(k_img, horizon)
            _, (latents, img_actions) = jax.lax.scan(
                img_step, (imagined_prior0, recurrent_state0, latent0), img_keys
            )
            imagined_trajectories = jnp.concatenate([latent0[None], latents], 0)  # [H+1, TB, L]
            zero_action = jnp.zeros_like(img_actions[:1])
            imagined_actions = jnp.concatenate([zero_action, img_actions], 0)  # [H+1, TB, A]

            # Predictions along the imagined rollout (target critic values)
            predicted_target_values = agent.critic_value(
                state["target_critic"], imagined_trajectories
            )
            predicted_rewards = agent.wm(
                state["world_model"], imagined_trajectories, method="reward"
            )
            if use_continues:
                continues = jax.nn.sigmoid(
                    agent.wm(state["world_model"], imagined_trajectories, method="continue_logits")
                )
                true_continue = (1 - data["terminated"]).reshape(1, -1, 1) * gamma
                continues = jnp.concatenate([true_continue, continues[1:]], 0)
            else:
                continues = jnp.ones_like(sg(predicted_rewards)) * gamma

            lambda_values = compute_lambda_values(
                predicted_rewards[:-1],
                predicted_target_values[:-1],
                continues[:-1],
                bootstrap=predicted_target_values[-1:],
                lmbda=lmbda,
            )
            discount = sg(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-1]], 0), 0)
            )

            # Actor objective: REINFORCE / dynamics mix (dreamer_v2.py:307-331)
            pre = agent.actor.apply(actor_params, sg(imagined_trajectories[:-2]))
            _, policies = dv2_actor_forward(pre, spec, k_actor, greedy=False)
            dynamics = lambda_values[1:]
            advantage = sg(lambda_values[1:] - predicted_target_values[:-2])
            if spec.is_continuous:
                logp = policies[0].log_prob(sg(imagined_actions[1:-1]))[..., None]
            else:
                splits = np.cumsum(actions_dim)[:-1]
                per_dim = jnp.split(imagined_actions, splits, -1)
                logp = jnp.stack(
                    [p.log_prob(sg(a[1:-1]))[..., None] for p, a in zip(policies, per_dim)], -1
                ).sum(-1)
            reinforce = logp * advantage
            objective = objective_mix * reinforce + (1 - objective_mix) * dynamics
            try:
                entropy = ent_coef * jnp.stack([p.entropy() for p in policies], -1).sum(-1)
                entropy = entropy[..., None] if entropy.ndim < objective.ndim else entropy
            except NotImplementedError:
                entropy = jnp.zeros_like(objective)
            policy_loss = -jnp.mean(sg(discount[:-2]) * (objective + entropy))
            img_aux = {
                "imagined_trajectories": sg(imagined_trajectories),
                "lambda_values": sg(lambda_values),
                "discount": discount,
            }
            return policy_loss, img_aux

        (policy_loss, img_aux), actor_grads = jax.value_and_grad(imagine_loss_fn, has_aux=True)(
            state["actor"]
        )
        actor_updates, actor_opt = txs["actor"].update(actor_grads, opt_states["actor"], state["actor"])
        state["actor"] = optax.apply_updates(state["actor"], actor_updates)

        # ------------------------------------------------- critic update
        traj = img_aux["imagined_trajectories"][:-1]
        lambda_values = img_aux["lambda_values"]
        discount = img_aux["discount"]

        def critic_loss_fn(critic_params):
            qv = Independent(Normal(agent.critic_value(critic_params, traj), 1.0), 1)
            return -jnp.mean(discount[:-1, ..., 0] * qv.log_prob(lambda_values))

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(state["critic"])
        critic_updates, critic_opt = txs["critic"].update(
            critic_grads, opt_states["critic"], state["critic"]
        )
        state["critic"] = optax.apply_updates(state["critic"], critic_updates)

        opt_states = {"world_model": wm_opt, "actor": actor_opt, "critic": critic_opt}
        metrics = {
            "Loss/world_model_loss": rec_loss,
            "Loss/observation_loss": aux["observation_loss"],
            "Loss/reward_loss": aux["reward_loss"],
            "Loss/state_loss": aux["state_loss"],
            "Loss/continue_loss": aux["continue_loss"],
            "State/kl": aux["kl"],
            "State/post_entropy": Independent(
                OneHotCategorical(logits=aux["posteriors_logits"]), 1
            ).entropy().mean(),
            "State/prior_entropy": Independent(
                OneHotCategorical(logits=aux["priors_logits"]), 1
            ).entropy().mean(),
            "Loss/policy_loss": policy_loss,
            "Loss/value_loss": value_loss,
            "Grads/world_model": optax.global_norm(wm_grads),
            "Grads/actor": optax.global_norm(actor_grads),
            "Grads/critic": optax.global_norm(critic_grads),
        }
        return state, opt_states, metrics, next_key

    return train_step


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    rank = runtime.global_rank
    world_size = jax.process_count()

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference: dreamer_v2.py:398-400)
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir}")
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    health = runtime.health

    envs = make_vector_env(cfg, rank, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    actions_dim, is_continuous = actions_metadata(action_space)
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    # Eager flax/optax init runs host-side (each eager dispatch pays the device-link round trip); shard_params then moves the finished trees to the mesh.
    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime,
            actions_dim,
            is_continuous,
            cfg,
            observation_space,
            state_ckpt["world_model"] if state_ckpt is not None else None,
            state_ckpt["actor"] if state_ckpt is not None else None,
            state_ckpt["critic"] if state_ckpt is not None else None,
            state_ckpt["target_critic"] if state_ckpt is not None else None,
        )

        txs = {
            "world_model": _make_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients),
            "actor": _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients),
            "critic": _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients),
        }
        opt_states = {
            "world_model": txs["world_model"].init(agent_state["world_model"]),
            "actor": txs["actor"].init(agent_state["actor"]),
            "critic": txs["critic"].init(agent_state["critic"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (
                ("world_model", "world_optimizer"),
                ("actor", "actor_optimizer"),
                ("critic", "critic_optimizer"),
            ):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])

    agent_state = runtime.shard_params(agent_state)
    opt_states = runtime.shard_params(opt_states)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(cfg.env.num_envs * world_size) if not cfg.dry_run else 4
    buffer_type = str(cfg.buffer.get("type", "sequential")).lower()
    if buffer_type == "sequential":
        rb = EnvIndependentReplayBuffer(
            buffer_size,
            n_envs=cfg.env.num_envs,
            obs_keys=obs_keys,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
            buffer_cls=SequentialReplayBuffer,
        )
    elif buffer_type == "episode":
        rb = EpisodeBuffer(
            buffer_size,
            minimum_episode_length=1 if cfg.dry_run else cfg.algo.per_rank_sequence_length,
            n_envs=cfg.env.num_envs,
            obs_keys=obs_keys,
            prioritize_ends=cfg.buffer.get("prioritize_ends", False),
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )
    else:
        raise ValueError(f"Unrecognized buffer type: must be one of `sequential` or `episode`, received: {buffer_type}")
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        rb = state_ckpt["rb"]

    train_step_count = 0
    last_train = 0
    start_iter = (state_ckpt["iter_num"] // world_size) + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * cfg.env.num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    train_fn = make_train_step(agent, txs, cfg, runtime.mesh)
    player_cnn_keys = tuple(cfg.algo.cnn_keys.encoder)

    def _player_step(wm, a, s, o, k):
        # PRNG split + obs normalization in-graph: ONE dispatch per env step.
        next_k, sub = jax.random.split(k)
        out = agent.player_step(
            wm, a, s, normalize_player_obs(o, player_cnn_keys), sub, greedy=False
        )
        return (*out, next_k)

    player_step_fn = jax.jit(_player_step)
    init_player_fn = jax.jit(agent.init_player_state, static_argnums=(1,))
    reset_player_fn = jax.jit(agent.reset_player_state)

    # Latency-aware player placement (core/player.py); off-policy: honors
    # fabric.player_sync=async. Mirror = world model + actor.
    placement = PlayerPlacement.resolve(
        cfg, runtime.mesh.devices.flat[0],
        params={"world_model": agent_state["world_model"], "actor": agent_state["actor"]},
    )
    placement.push({"world_model": agent_state["world_model"], "actor": agent_state["actor"]})


    # Async infeed (data/infeed.py): the next train call's sampled batches
    # are copied host->device by a worker thread while envs step, so the
    # pixel-batch H2D never sits on the critical path.
    infeed = ReplayInfeed(
        rb,
        cfg.algo.per_rank_batch_size,
        cfg.algo.per_rank_sequence_length,
        cfg.algo.cnn_keys.encoder,
        enabled=cfg.buffer.get("prefetch", True),
    )

    rollout_key, train_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key = placement.put(rollout_key)

    # Async-capable action fetch (core/interact.py): with fabric.async_fetch
    # the D2H copy is submitted at dispatch time and harvested right before
    # envs.step; off it is op-for-op the old blocking fetch.
    pipeline = InteractionPipeline.from_config(cfg)

    step_data = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = obs[k][np.newaxis]
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1), np.float32)
    if cfg.dry_run:
        step_data["terminated"] = step_data["terminated"] + 1
        step_data["truncated"] = step_data["truncated"] + 1
    step_data["actions"] = np.zeros((1, cfg.env.num_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    rb.add(step_data, validate_args=cfg.buffer.validate_args)
    with placement.ctx():
        player_state = init_player_fn(placement.params()["world_model"], cfg.env.num_envs)

    cumulative_per_rank_gradient_steps = 0
    # Bound async in-flight train dispatches (core/runtime.py: an
    # unbounded queue pins every pending call's sampled batch on host).
    dispatch_throttle = DispatchThrottle()
    # Coalesced loss fetch + interval bounding (telemetry/step_timer.py):
    # ONE block_until_ready + ONE device_get per log interval.
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    keep_train_metrics = (
        aggregator is not None and not aggregator.disabled and cfg.metric.log_level > 0
    ) or health.enabled
    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter
        telemetry.advance(policy_step)
        guard.advance(policy_step)

        pending = None
        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and cfg.checkpoint.resume_from is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                with placement.ctx():
                    np_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=cfg.env.num_envs)
                    pp = placement.params()
                    actions_cat, real_actions_j, player_state, rollout_key = player_step_fn(
                        pp["world_model"], pp["actor"], player_state, np_obs, rollout_key
                    )
                # One host fetch for both arrays: each separate np.asarray
                # is a full device->host roundtrip (painful over a tunneled
                # chip). Submitted at dispatch, harvested after the is_first
                # bookkeeping so the copy rides under that host work.
                pending = pipeline.fetch((actions_cat, real_actions_j), label="player_actions")

            step_data["is_first"] = copy.deepcopy(
                np.logical_or(step_data["terminated"], step_data["truncated"]).astype(np.float32)
            )
            if pending is not None:
                actions, real_actions = pending.harvest()
            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)
            if cfg.dry_run and buffer_type == "episode":
                dones = np.ones_like(dones)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            for i in np.nonzero(fi.get("_episode", []))[0]:
                ep_rew = float(fi["episode"]["r"][i])
                ep_len = float(fi["episode"]["l"][i])
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        real_next_obs = copy.deepcopy(next_obs)
        if "final_obs" in infos:
            for idx in np.nonzero(dones)[0]:
                final = infos["final_obs"][idx]
                if final is not None:
                    for k, v in final.items():
                        real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = real_next_obs[k][np.newaxis]
        obs = next_obs

        step_data["terminated"] = terminated.reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        step_data["truncated"] = truncated.reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        if cfg.dry_run and buffer_type == "episode":
            step_data["terminated"] = np.ones_like(step_data["terminated"])
            step_data["truncated"] = np.ones_like(step_data["truncated"])
        step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        step_data["rewards"] = clip_rewards_fn(rewards).reshape((1, cfg.env.num_envs, -1)).astype(np.float32)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = np.zeros((1, reset_envs, 1), np.float32)
            reset_data["truncated"] = np.zeros((1, reset_envs, 1), np.float32)
            reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, reset_envs, 1), np.float32)
            reset_data["is_first"] = np.ones_like(reset_data["terminated"])
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            for d in dones_idxes:
                step_data["terminated"][0, d] = np.zeros_like(step_data["terminated"][0, d])
                step_data["truncated"][0, d] = np.zeros_like(step_data["truncated"][0, d])
            reset_mask = np.zeros((cfg.env.num_envs,), np.float32)
            reset_mask[dones_idxes] = 1.0
            with placement.ctx():
                player_state = reset_player_fn(
                    placement.params()["world_model"], player_state, jnp.asarray(reset_mask)
                )

        # ------------------------------------------------------- training
        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                batches = infeed.take_or_sample(per_rank_gradient_steps)
                with timer("Time/train_time"):
                    for i in range(per_rank_gradient_steps):
                        if (
                            cumulative_per_rank_gradient_steps
                            % cfg.algo.critic.per_rank_target_network_update_freq
                            == 0
                        ):
                            # Hard copy critic → target critic (reference:
                            # dreamer_v2.py:697-703), host-side.
                            agent_state["target_critic"] = jax.tree_util.tree_map(
                                jnp.copy, agent_state["critic"]
                            )
                        batch = batches[i]
                        with train_timer.step():
                            agent_state, opt_states, train_metrics, train_key = train_fn(
                                agent_state, opt_states, batch, train_key
                            )
                        # No sync here: the StepTimer queues the loss scalars
                        # device-side and bounds the interval with ONE block
                        # at the log-interval flush.
                        train_timer.pend(
                            agent_state["world_model"],
                            train_metrics if keep_train_metrics else None,
                        )
                        dispatch_throttle.add(train_metrics)
                        cumulative_per_rank_gradient_steps += 1
                    placement.push(
                        {"world_model": agent_state["world_model"], "actor": agent_state["actor"]}
                    )
                    train_step_count += world_size
                # Sample on the main thread (no buffer race); stage the device
                # copies to overlap the next env-step phase.
                infeed.stage(per_rank_gradient_steps)

        # -------------------------------------------------------- logging
        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            # The interval's losses in ONE bounding block + ONE device->host
            # transfer (StepTimer.flush) — the coalesced pattern GL002 asks
            # for, now owned by telemetry.
            fetched_train_metrics = train_timer.flush()
            # Health sentinels inspect the same coalesced fetch — no extra
            # transfer; a nonfinite hit taints the run and escalates.
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            if aggregator and not aggregator.disabled:
                for m in fetched_train_metrics:
                    for k, v in m.items():
                        if k in aggregator:
                            aggregator.update(k, v)
                # Collective when sync_on_compute is on: every rank joins;
                # only rank 0 (the only rank with a logger) writes.
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            if policy_step > 0:
                logger.log(
                    "Params/replay_ratio",
                    cumulative_per_rank_gradient_steps * world_size / policy_step,
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log(
                        "Time/sps_env_interaction",
                        ((policy_step - last_log) / world_size * cfg.env.action_repeat)
                        / timer_metrics["Time/env_interaction_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        # ----------------------------------------------------- checkpoint
        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": agent_state["world_model"],
                "actor": agent_state["actor"],
                "critic": agent_state["critic"],
                "target_critic": agent_state["target_critic"],
                "world_optimizer": opt_states["world_model"],
                "actor_optimizer": opt_states["actor"],
                "critic_optimizer": opt_states["critic"],
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            if cfg.buffer.checkpoint:
                ckpt_state["rb"] = rb
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break
    infeed.close()
    pipeline.publish()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, agent_state, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
