"""DreamerV2 agent (flax): world model (discrete-latent RSSM), actor, critic.

Capability parity with the reference agent
(sheeprl/algos/dreamer_v2/agent.py:40-1104), re-designed for XLA like the
DreamerV3 agent in this package: single-step pure RSSM methods scanned by the
training step, NHWC pixels, functional player state.

DV2-specific facts (vs the V3 agent next door):
- No unimix on categorical logits; posterior/prior sampled straight from the
  representation/transition outputs (agent.py:389-414).
- Reset mixing zeroes the states — there is no learned initial recurrent
  state (RSSM.dynamic, agent.py:364-370).
- ELU activations, LayerNorm OFF by default (configs/algo/dreamer_v2.yaml),
  xavier-normal initialization everywhere (utils.py:64-82).
- Encoder convs are k4/s2 with NO padding (agent.py:63-75: 64→31→14→6→2);
  the decoder projects the latent to a 1×1 feature map and deconvs with
  kernels [5,5,6,6]/s2 back to 64×64 (agent.py:169-188).
- Reward/critic heads are scalar Normal(·, 1) — no two-hot bins.
- The continue head is optional (`use_continues`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.algos.dreamer_v3.agent import compute_stochastic_state
from sheeprl_tpu.models import MLP, CNN, DeCNN, LayerNormGRUCell
from sheeprl_tpu.utils.distribution import (
    Independent,
    Normal,
    OneHotCategoricalStraightThrough,
    TruncatedNormal,
)

xavier_normal_init = jax.nn.initializers.glorot_normal()


def conv_out_size(size: int, kernel: int, stride: int, padding: int = 0) -> int:
    return (size + 2 * padding - kernel) // stride + 1


def cnn_encoder_output_dim(image_size: Tuple[int, int], channels_multiplier: int, stages: int = 4) -> int:
    h, w = image_size
    for _ in range(stages):
        h = conv_out_size(h, 4, 2)
        w = conv_out_size(w, 4, 2)
    return h * w * (2 ** (stages - 1)) * channels_multiplier


class DV2CNNEncoder(nn.Module):
    """4-stage conv encoder, k4/s2/p0, channels [1,2,4,8]*multiplier, NHWC
    (reference: CNNEncoder, agent.py:40-81)."""

    keys: Sequence[str]
    channels_multiplier: int
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        x = CNN(
            hidden_channels=[(2**i) * self.channels_multiplier for i in range(4)],
            layer_args={"kernel_size": 4, "stride": 2, "padding": 0},
            activation=self.activation,
            norm_layer="layer_norm" if self.layer_norm else None,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="model",
        )(x)
        return x.reshape(*x.shape[:-3], -1)


class DV2MLPEncoder(nn.Module):
    """Plain vector encoder, no symlog (reference: MLPEncoder, agent.py:84-128)."""

    keys: Sequence[str]
    mlp_layers: int = 4
    dense_units: int = 400
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            norm_layer="layer_norm" if self.layer_norm else None,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="model",
        )(x)


class DV2CNNDecoder(nn.Module):
    """Latent → Dense → 1×1 feature map → 4 deconv stages k[5,5,6,6]/s2 →
    per-key HWC reconstructions (reference: CNNDecoder, agent.py:131-196)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    image_size: Tuple[int, int]
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        batch_shape = latent_states.shape[:-1]
        x = nn.Dense(
            self.cnn_encoder_output_dim, kernel_init=xavier_normal_init, dtype=self.dtype, name="fc"
        )(latent_states)
        x = x.reshape(-1, 1, 1, self.cnn_encoder_output_dim)
        out_ch = int(sum(self.output_channels))
        norm = "layer_norm" if self.layer_norm else None
        x = DeCNN(
            hidden_channels=[4 * self.channels_multiplier, 2 * self.channels_multiplier,
                             self.channels_multiplier, out_ch],
            layer_args=[
                {"kernel_size": 5, "stride": 2},
                {"kernel_size": 5, "stride": 2},
                {"kernel_size": 6, "stride": 2},
                {"kernel_size": 6, "stride": 2},
            ],
            activation=[self.activation] * 3 + [None],
            norm_layer=[norm] * 3 + [None],
            norm_args=[{} if self.layer_norm else None] * 3 + [None],
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="model",
        )(x)
        x = x.reshape(*batch_shape, *self.image_size, out_ch)
        splits = np.cumsum(self.output_channels)[:-1]
        return {k: v for k, v in zip(self.keys, jnp.split(x, splits, axis=-1))}


class DV2MLPDecoder(nn.Module):
    """Shared trunk + one linear head per key (reference: MLPDecoder,
    agent.py:199-246)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 400
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            norm_layer="layer_norm" if self.layer_norm else None,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="model",
        )(latent_states)
        return {
            k: nn.Dense(dim, kernel_init=xavier_normal_init, dtype=self.dtype, name=f"head_{i}")(x)
            for i, (k, dim) in enumerate(zip(self.keys, self.output_dims))
        }


class DV2RecurrentModel(nn.Module):
    """Dense+ELU projection into a LayerNormGRUCell (reference:
    RecurrentModel, agent.py:248-298; GRU layer-norm ON by default in DV2)."""

    recurrent_state_size: int
    dense_units: int
    activation: str = "elu"
    layer_norm: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = MLP(
            hidden_sizes=[self.dense_units],
            activation=self.activation,
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="mlp",
        )(x)
        return LayerNormGRUCell(
            hidden_size=self.recurrent_state_size,
            bias=True,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            name="rnn",
        )(recurrent_state, feat)


class DV2WorldModel(nn.Module):
    """Encoder + RSSM + decoders + reward (+ optional continue) heads as one
    module with method-based apply (reference: WorldModel container at
    agent.py:707-733 + RSSM at agent.py:301-414)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_input_channels: Sequence[int]
    mlp_input_dims: Sequence[int]
    image_size: Tuple[int, int]
    actions_dim: Sequence[int]
    stochastic_size: int = 32
    discrete_size: int = 32
    recurrent_state_size: int = 600
    recurrent_dense_units: int = 400
    recurrent_layer_norm: bool = True
    transition_hidden_size: int = 600
    representation_hidden_size: int = 600
    encoder_cnn_channels_multiplier: int = 48
    encoder_mlp_layers: int = 4
    encoder_dense_units: int = 400
    decoder_cnn_channels_multiplier: int = 48
    decoder_mlp_layers: int = 4
    decoder_dense_units: int = 400
    reward_mlp_layers: int = 4
    reward_dense_units: int = 400
    continue_mlp_layers: int = 4
    continue_dense_units: int = 400
    use_continues: bool = False
    cnn_act: str = "elu"
    dense_act: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size * self.discrete_size

    @property
    def latent_state_size(self) -> int:
        return self.stoch_state_size + self.recurrent_state_size

    def setup(self) -> None:
        norm = "layer_norm" if self.layer_norm else None
        self.cnn_encoder = (
            DV2CNNEncoder(
                keys=self.cnn_keys,
                channels_multiplier=self.encoder_cnn_channels_multiplier,
                activation=self.cnn_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        self.mlp_encoder = (
            DV2MLPEncoder(
                keys=self.mlp_keys,
                mlp_layers=self.encoder_mlp_layers,
                dense_units=self.encoder_dense_units,
                activation=self.dense_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.recurrent_model = DV2RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.recurrent_dense_units,
            activation=self.dense_act,
            layer_norm=self.recurrent_layer_norm,
            dtype=self.dtype,
        )
        self.representation_model = MLP(
            hidden_sizes=[self.representation_hidden_size],
            output_dim=self.stoch_state_size,
            activation=self.dense_act,
            norm_layer=norm,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            output_kernel_init=xavier_normal_init,
            dtype=self.dtype,
        )
        self.transition_model = MLP(
            hidden_sizes=[self.transition_hidden_size],
            output_dim=self.stoch_state_size,
            activation=self.dense_act,
            norm_layer=norm,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            output_kernel_init=xavier_normal_init,
            dtype=self.dtype,
        )
        enc_out = cnn_encoder_output_dim(self.image_size, self.encoder_cnn_channels_multiplier)
        self.cnn_decoder = (
            DV2CNNDecoder(
                keys=self.cnn_keys,
                output_channels=self.cnn_input_channels,
                channels_multiplier=self.decoder_cnn_channels_multiplier,
                cnn_encoder_output_dim=enc_out,
                image_size=self.image_size,
                activation=self.cnn_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        self.mlp_decoder = (
            DV2MLPDecoder(
                keys=self.mlp_keys,
                output_dims=self.mlp_input_dims,
                mlp_layers=self.decoder_mlp_layers,
                dense_units=self.decoder_dense_units,
                activation=self.dense_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.reward_model = MLP(
            hidden_sizes=[self.reward_dense_units] * self.reward_mlp_layers,
            output_dim=1,
            activation=self.dense_act,
            norm_layer=norm,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            output_kernel_init=xavier_normal_init,
            dtype=self.dtype,
        )
        self.continue_model = (
            MLP(
                hidden_sizes=[self.continue_dense_units] * self.continue_mlp_layers,
                output_dim=1,
                activation=self.dense_act,
                norm_layer=norm,
                norm_args={} if self.layer_norm else None,
                kernel_init=xavier_normal_init,
                output_kernel_init=xavier_normal_init,
                dtype=self.dtype,
            )
            if self.use_continues
            else None
        )

    # --------------------------------------------------------------- encoder
    def embed_obs(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        return jnp.concatenate(outs, axis=-1) if len(outs) > 1 else outs[0]

    # ------------------------------------------------------------------ rssm
    def _representation(
        self, recurrent_state: jax.Array, embedded_obs: jax.Array, key: Optional[jax.Array]
    ) -> Tuple[jax.Array, jax.Array]:
        logits = self.representation_model(
            jnp.concatenate([recurrent_state, embedded_obs], axis=-1)
        )
        post = compute_stochastic_state(logits, self.discrete_size, key)
        return logits, post.reshape(*post.shape[:-2], -1)

    def _transition(
        self, recurrent_out: jax.Array, key: Optional[jax.Array], sample_state: bool = True
    ) -> Tuple[jax.Array, jax.Array]:
        logits = self.transition_model(recurrent_out)
        prior = compute_stochastic_state(logits, self.discrete_size, key, sample=sample_state)
        return logits, prior.reshape(*prior.shape[:-2], -1)

    def dynamic(
        self,
        posterior: jax.Array,
        recurrent_state: jax.Array,
        action: jax.Array,
        embedded_obs: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
        """One step of dynamic learning (reference: RSSM.dynamic,
        agent.py:332-371): is_first zeroes state and action (no learned
        initial state in DV2), GRU step, prior + posterior."""
        k1, k2 = jax.random.split(key)
        action = (1 - is_first) * action
        posterior = (1 - is_first) * posterior
        recurrent_state = (1 - is_first) * recurrent_state
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        prior_logits, prior = self._transition(recurrent_state, k1)
        posterior_logits, posterior = self._representation(recurrent_state, embedded_obs, k2)
        return recurrent_state, posterior, prior, posterior_logits, prior_logits

    def imagination(
        self, prior: jax.Array, recurrent_state: jax.Array, actions: jax.Array, key: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """One-step latent imagination (reference: RSSM.imagination,
        agent.py:396-414)."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([prior, actions], -1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key)
        return imagined_prior, recurrent_state

    # ----------------------------------------------------------------- heads
    def decode(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent_states))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent_states))
        return out

    def reward(self, latent_states: jax.Array) -> jax.Array:
        return self.reward_model(latent_states)

    def continue_logits(self, latent_states: jax.Array) -> jax.Array:
        if self.continue_model is None:
            raise ValueError("use_continues is False: the continue model does not exist")
        return self.continue_model(latent_states)

    def __call__(self, obs: Dict[str, jax.Array], actions: jax.Array, key: jax.Array):
        """Init-only pass touching every submodule once."""
        embedded = self.embed_obs(obs)
        batch = embedded.shape[:-1]
        h0 = jnp.zeros((*batch, self.recurrent_state_size), self.dtype)
        z0 = jnp.zeros((*batch, self.stoch_state_size), self.dtype)
        h, post, prior, post_logits, prior_logits = self.dynamic(
            z0, h0, actions, embedded, jnp.zeros((*batch, 1), self.dtype), key
        )
        latent = jnp.concatenate([post, h], -1)
        out = (self.decode(latent), self.reward(latent))
        if self.continue_model is not None:
            out = out + (self.continue_logits(latent),)
        return out


class DV2Actor(nn.Module):
    """DV2 actor: ELU MLP trunk + one head per action dim (reference: Actor,
    agent.py:416-529). Raw head outputs; distributions in
    `dv2_actor_forward`."""

    actions_dim: Sequence[int]
    is_continuous: bool
    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array) -> List[jax.Array]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            norm_layer="layer_norm" if self.layer_norm else None,
            norm_args={} if self.layer_norm else None,
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="model",
        )(state)
        if self.is_continuous:
            return [
                nn.Dense(
                    int(np.sum(self.actions_dim)) * 2,
                    kernel_init=xavier_normal_init,
                    dtype=self.dtype,
                    name="head_0",
                )(x)
            ]
        return [
            nn.Dense(dim, kernel_init=xavier_normal_init, dtype=self.dtype, name=f"head_{i}")(x)
            for i, dim in enumerate(self.actions_dim)
        ]


@dataclass(frozen=True)
class DV2ActorSpec:
    """Distribution metadata (reference Actor attributes, agent.py:458-501):
    continuous default is trunc_normal on [-1, 1]."""

    actions_dim: Tuple[int, ...]
    is_continuous: bool
    distribution: str  # discrete | trunc_normal | tanh_normal | normal
    init_std: float = 0.0
    min_std: float = 0.1
    expl_amount: float = 0.0
    expl_decay: float = 0.0
    expl_min: float = 0.0


def _dv2_continuous_dist(pre_dist: jax.Array, spec: DV2ActorSpec):
    mean, std = jnp.split(pre_dist, 2, axis=-1)
    if spec.distribution == "tanh_normal":
        mean = 5 * jnp.tanh(mean / 5)
        std = jax.nn.softplus(std + spec.init_std) + spec.min_std
        return Independent(Normal(mean, std), 1), True
    if spec.distribution == "normal":
        return Independent(Normal(mean, std), 1), False
    # trunc_normal (continuous default, agent.py:536-539)
    std = 2 * jax.nn.sigmoid((std + spec.init_std) / 2) + spec.min_std
    return Independent(TruncatedNormal(jnp.tanh(mean), std, -1.0, 1.0), 1), False


def dv2_actor_forward(
    pre_dist: List[jax.Array],
    spec: DV2ActorSpec,
    key: Optional[jax.Array] = None,
    greedy: bool = False,
) -> Tuple[List[jax.Array], List[Any]]:
    """Head outputs → (sampled actions, distributions)
    (reference: Actor.forward, agent.py:506-556)."""
    if spec.is_continuous:
        dist, tanh_transformed = _dv2_continuous_dist(pre_dist[0], spec)
        if not greedy:
            actions = dist.rsample(key)
        else:
            sample = dist.sample(key, (100,))
            log_prob = dist.log_prob(sample)
            idx = jnp.argmax(log_prob, axis=0)
            actions = jnp.take_along_axis(sample, idx[None, ..., None], axis=0)[0]
        if tanh_transformed:
            actions = jnp.tanh(actions)
        return [actions], [dist]
    dists = []
    actions = []
    keys = jax.random.split(key, len(pre_dist)) if key is not None else [None] * len(pre_dist)
    for logits, k in zip(pre_dist, keys):
        d = OneHotCategoricalStraightThrough(logits=logits)
        dists.append(d)
        actions.append(d.mode if greedy else d.rsample(k))
    return actions, dists


def add_exploration_noise(
    actions: jax.Array, spec: DV2ActorSpec, amount: jax.Array, key: jax.Array, actions_dim: Sequence[int]
) -> jax.Array:
    """Exploration noise on concatenated actions (reference:
    Actor.add_exploration_noise, agent.py:558-574): Normal jitter clipped to
    [-1, 1] for continuous, epsilon-resampling per head for discrete."""
    if spec.is_continuous:
        noisy = jnp.clip(actions + amount * jax.random.normal(key, actions.shape, actions.dtype), -1, 1)
        return jnp.where(amount > 0, noisy, actions)
    splits = np.cumsum(np.asarray(actions_dim))[:-1]
    out = []
    for act, k in zip(jnp.split(actions, splits, -1), jax.random.split(key, len(actions_dim))):
        k_cat, k_mask = jax.random.split(k)
        rand = OneHotCategoricalStraightThrough(logits=jnp.zeros_like(act)).sample(k_cat)
        take_rand = jax.random.uniform(k_mask, act.shape[:1]) < amount
        out.append(jnp.where(take_rand[..., None], rand, act))
    return jnp.concatenate(out, -1)


@dataclass(frozen=True)
class DV2Agent:
    """Bundles the modules + metadata; params live in the train state
    {world_model, actor, critic, target_critic}."""

    world_model: DV2WorldModel
    actor: DV2Actor
    critic: Any  # MLP
    actor_spec: DV2ActorSpec
    actions_dim: Tuple[int, ...]
    is_continuous: bool

    def wm(self, params, *args, method: str):
        return self.world_model.apply(params, *args, method=getattr(DV2WorldModel, method))

    def critic_value(self, params, latent: jax.Array) -> jax.Array:
        return self.critic.apply(params, latent)

    # ---------------------------------------------------------------- player
    def init_player_state(self, wm_params, n_envs: int) -> Dict[str, jax.Array]:
        """Zero player state (reference: PlayerDV2.init_states,
        agent.py:778-800 — DV2 has no learned initial state)."""
        del wm_params  # kept for API parity with the DV3 player
        return {
            "recurrent_state": jnp.zeros((n_envs, self.world_model.recurrent_state_size)),
            "stochastic_state": jnp.zeros((n_envs, self.world_model.stoch_state_size)),
            "actions": jnp.zeros((n_envs, int(np.sum(self.actions_dim)))),
        }

    def reset_player_state(
        self, wm_params, state: Dict[str, jax.Array], reset_mask: jax.Array
    ) -> Dict[str, jax.Array]:
        m = reset_mask[..., None]
        return {k: (1 - m) * v for k, v in state.items()}

    def player_step(
        self,
        wm_params,
        actor_params,
        state: Dict[str, jax.Array],
        obs: Dict[str, jax.Array],
        key: jax.Array,
        greedy: bool = False,
    ):
        """One acting step (reference: PlayerDV2.get_actions, agent.py:802-832).
        Returns (actions_cat, real_actions, new_state)."""
        k1, k2 = jax.random.split(key)
        embedded = self.wm(wm_params, obs, method="embed_obs")
        recurrent_state = self.world_model.apply(
            wm_params,
            jnp.concatenate([state["stochastic_state"], state["actions"]], -1),
            state["recurrent_state"],
            method=lambda wm, x, h: wm.recurrent_model(x, h),
        )
        _, stochastic_state = self.world_model.apply(
            wm_params, recurrent_state, embedded, k1, method=DV2WorldModel._representation
        )
        latent = jnp.concatenate([stochastic_state, recurrent_state], -1)
        pre_dist = self.actor.apply(actor_params, latent)
        actions, _ = dv2_actor_forward(pre_dist, self.actor_spec, k2, greedy)
        actions_cat = jnp.concatenate(actions, -1)
        if self.is_continuous:
            real_actions = actions_cat
        else:
            real_actions = jnp.stack([jnp.argmax(a, -1) for a in actions], -1)
        new_state = {
            "recurrent_state": recurrent_state,
            "stochastic_state": stochastic_state,
            "actions": actions_cat,
        }
        return actions_cat, real_actions, new_state


def build_world_model_module(cfg: Dict[str, Any], obs_space, actions_dim, dtype) -> DV2WorldModel:
    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    return DV2WorldModel(
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_input_channels=tuple(int(obs_space[k].shape[-1]) for k in cnn_keys),
        mlp_input_dims=tuple(int(obs_space[k].shape[0]) for k in mlp_keys),
        image_size=tuple(obs_space[cnn_keys[0]].shape[:2]) if cnn_keys else (64, 64),
        actions_dim=tuple(actions_dim),
        stochastic_size=wm_cfg.stochastic_size,
        discrete_size=wm_cfg.discrete_size,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        recurrent_dense_units=wm_cfg.recurrent_model.dense_units,
        recurrent_layer_norm=bool(wm_cfg.recurrent_model.layer_norm),
        transition_hidden_size=wm_cfg.transition_model.hidden_size,
        representation_hidden_size=wm_cfg.representation_model.hidden_size,
        encoder_cnn_channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        encoder_mlp_layers=wm_cfg.encoder.mlp_layers,
        encoder_dense_units=wm_cfg.encoder.dense_units,
        decoder_cnn_channels_multiplier=wm_cfg.observation_model.cnn_channels_multiplier,
        decoder_mlp_layers=wm_cfg.observation_model.mlp_layers,
        decoder_dense_units=wm_cfg.observation_model.dense_units,
        reward_mlp_layers=wm_cfg.reward_model.mlp_layers,
        reward_dense_units=wm_cfg.reward_model.dense_units,
        continue_mlp_layers=wm_cfg.discount_model.mlp_layers,
        continue_dense_units=wm_cfg.discount_model.dense_units,
        use_continues=bool(wm_cfg.use_continues),
        cnn_act="elu",
        dense_act="elu",
        layer_norm=bool(cfg.algo.layer_norm),
        dtype=dtype,
    )


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    actor_state: Optional[Any] = None,
    critic_state: Optional[Any] = None,
    target_critic_state: Optional[Any] = None,
) -> Tuple[DV2Agent, Dict[str, Any]]:
    """Construct modules + initial (or restored) params
    (reference: build_agent, agent.py:835-1104)."""
    dtype = runtime.precision.compute_dtype
    distribution = str(cfg.distribution.get("type", "auto")).lower()
    if distribution not in ("auto", "normal", "tanh_normal", "discrete", "trunc_normal"):
        raise ValueError(
            "The distribution must be on of: `auto`, `discrete`, `normal`, `tanh_normal` and `trunc_normal`. "
            f"Found: {distribution}"
        )
    if distribution == "discrete" and is_continuous:
        raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
    if distribution == "auto":
        distribution = "trunc_normal" if is_continuous else "discrete"

    wm = build_world_model_module(cfg, obs_space, actions_dim, dtype)
    actor = DV2Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        activation="elu",
        layer_norm=bool(cfg.algo.layer_norm),
        dtype=dtype,
    )
    critic = MLP(
        hidden_sizes=[cfg.algo.critic.dense_units] * cfg.algo.critic.mlp_layers,
        output_dim=1,
        activation="elu",
        norm_layer="layer_norm" if cfg.algo.layer_norm else None,
        norm_args={} if cfg.algo.layer_norm else None,
        kernel_init=xavier_normal_init,
        output_kernel_init=xavier_normal_init,
        dtype=dtype,
    )
    spec = DV2ActorSpec(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=is_continuous,
        distribution=distribution,
        init_std=cfg.algo.actor.init_std,
        min_std=cfg.algo.actor.min_std,
        expl_amount=float(cfg.algo.actor.get("expl_amount", 0.0)),
        expl_decay=float(cfg.algo.actor.get("expl_decay", 0.0)),
        expl_min=float(cfg.algo.actor.get("expl_min", 0.0)),
    )
    agent = DV2Agent(
        world_model=wm,
        actor=actor,
        critic=critic,
        actor_spec=spec,
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=is_continuous,
    )

    k_wm, k_actor, k_critic, k_call = jax.random.split(runtime.root_key, 4)
    n = 1
    dummy_obs = {
        k: jnp.zeros((n, *obs_space[k].shape), jnp.float32)
        for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    }
    dummy_actions = jnp.zeros((n, int(np.sum(actions_dim))), jnp.float32)
    latent_size = wm.latent_state_size

    if world_model_state is not None:
        wm_params = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    else:
        wm_params = wm.init({"params": k_wm, "sample": k_call}, dummy_obs, dummy_actions, k_call)
    actor_params = (
        jax.tree_util.tree_map(jnp.asarray, actor_state)
        if actor_state is not None
        else actor.init(k_actor, jnp.zeros((n, latent_size), jnp.float32))
    )
    critic_params = (
        jax.tree_util.tree_map(jnp.asarray, critic_state)
        if critic_state is not None
        else critic.init(k_critic, jnp.zeros((n, latent_size), jnp.float32))
    )
    target_critic_params = (
        jax.tree_util.tree_map(jnp.asarray, target_critic_state)
        if target_critic_state is not None
        else jax.tree_util.tree_map(jnp.copy, critic_params)
    )
    state = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": target_critic_params,
    }
    return agent, state
