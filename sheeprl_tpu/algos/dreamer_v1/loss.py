"""DreamerV1 losses (reference: sheeprl/algos/dreamer_v1/loss.py:9-100).

Eq. 7/8/10 of the Dreamer paper: actor loss is the negated mean of the
discounted λ-values, critic is a Normal log-likelihood of the λ-targets, the
world-model loss combines decoder/reward likelihoods with a free-nats-floored
Normal KL.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.distribution import kl_divergence


def actor_loss(discounted_lambda_values: jax.Array) -> jax.Array:
    return -jnp.mean(discounted_lambda_values)


def critic_loss(qv, lambda_values: jax.Array, discount: jax.Array) -> jax.Array:
    return -jnp.mean(discount * qv.log_prob(lambda_values))


def reconstruction_loss(
    qo: Dict[str, object],
    observations: Dict[str, jax.Array],
    qr,
    rewards: jax.Array,
    posteriors_dist,
    priors_dist,
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    qc=None,
    continue_targets: Optional[jax.Array] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (reconstruction_loss, kl, state_loss, reward_loss,
    observation_loss, continue_loss).

    Divergence from the reference (documented): the reference adds
    `+ qc.log_prob(targets)` un-negated and un-reduced (loss.py:92-95), which
    cannot be a scalar loss term — the continue head is off by default there
    and that path is untested. Here the continue loss is the usual negated
    mean log-likelihood.
    """
    observation_loss = -sum(qo[k].log_prob(observations[k]).mean() for k in qo)
    reward_loss = -qr.log_prob(rewards).mean()
    kl = kl_divergence(posteriors_dist, priors_dist).mean()
    state_loss = jnp.maximum(kl, jnp.asarray(kl_free_nats, kl.dtype))
    if qc is not None and continue_targets is not None:
        continue_loss = continue_scale_factor * -qc.log_prob(continue_targets).mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    total = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    return total, kl, state_loss, reward_loss, observation_loss, continue_loss
