"""DreamerV1 auxiliary contract (reference: sheeprl/algos/dreamer_v1/utils.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.utils import normalize_player_obs, prepare_obs, test  # noqa: F401 (re-export)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/post_entropy",
    "State/prior_entropy",
    "State/kl",
    "Params/exploration_amount",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    last_values: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV1-style λ-targets over [H, ...] arrays → [H-1, ...]
    (reference reverse loop: dreamer_v1/utils.py compute_lambda_values):
    delta[t] = r[t] + c[t] * next_v[t], where next_v is (1-λ)V[t+1] except at
    the last step where it is the full bootstrap value;
    L[t] = delta[t] + λ c[t] L[t+1], seeded with 0. fp32 accumulation.
    """
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    continues = continues.astype(jnp.float32)
    last_values = last_values.astype(jnp.float32)
    H = rewards.shape[0]
    next_values = jnp.concatenate([values[1 : H - 1] * (1 - lmbda), last_values[None]], axis=0)
    deltas = rewards[: H - 1] + next_values * continues[: H - 1]

    def step(nxt, x):
        d, c = x
        v = d + lmbda * c * nxt
        return v, v

    _, out = jax.lax.scan(
        step, jnp.zeros_like(deltas[0]), (deltas, continues[: H - 1]), reverse=True
    )
    return out


def exploration_amount(spec, step: int) -> float:
    """Host-side exploration schedule (reference: Actor._get_expl_amount,
    dreamer_v2/agent.py:499-503)."""
    amount = spec.expl_amount
    if spec.expl_decay:
        amount *= 0.5 ** (float(step) / spec.expl_decay)
    return max(amount, spec.expl_min)
