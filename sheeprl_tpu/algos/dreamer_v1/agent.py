"""DreamerV1 agent (flax): continuous-latent RSSM world model, actor, critic.

Capability parity with the reference agent
(sheeprl/algos/dreamer_v1/agent.py:31-547). DV1 reuses the DV2
encoder/decoder/actor modules (the reference does the same,
agent.py:16-19); what is specific here:

- The stochastic state is a CONTINUOUS diagonal Normal of size
  `stochastic_size` (default 30): the representation/transition MLPs emit
  (mean, std) chunks, std = softplus(std) + min_std
  (dreamer_v1/utils.py compute_stochastic_state).
- The recurrent model is Dense+ELU into a STANDARD GRU cell (torch nn.GRU,
  agent.py:42-61) — not the Hafner LayerNorm GRU — so `flax.linen.GRUCell`
  is the exact analog.
- `dynamic` has no is_first reset handling (agent.py:97-134); episode starts
  are only implicit in the zero initial states.
- The player adds exploration noise (expl_amount=0.3 with optional decay,
  reference get_exploration_actions, agent.py:278-300).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.algos.dreamer_v2.agent import (
    DV2Actor,
    DV2ActorSpec,
    DV2CNNDecoder,
    DV2CNNEncoder,
    DV2MLPDecoder,
    DV2MLPEncoder,
    add_exploration_noise,
    dv2_actor_forward,
    xavier_normal_init,
)
from sheeprl_tpu.models import MLP
from sheeprl_tpu.utils.distribution import Independent, Normal


def compute_stochastic_state_v1(
    state_information: jax.Array, key: Optional[jax.Array], min_std: float = 0.1
) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
    """(mean, std), sample from the (mean, raw-std) chunks emitted by the
    representation/transition models (reference: dreamer_v1/utils.py
    compute_stochastic_state)."""
    mean, std = jnp.split(state_information, 2, axis=-1)
    std = jax.nn.softplus(std) + min_std
    dist = Independent(Normal(mean, std), 1)
    sample = dist.rsample(key) if key is not None else mean
    return (mean, std), sample


class DV1RecurrentModel(nn.Module):
    """Dense+ELU into a standard GRU cell (reference: RecurrentModel,
    agent.py:31-61)."""

    recurrent_state_size: int
    activation: str = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = MLP(
            hidden_sizes=[self.recurrent_state_size],
            activation=self.activation,
            kernel_init=xavier_normal_init,
            dtype=self.dtype,
            name="mlp",
        )(x)
        new_h, _ = nn.GRUCell(
            features=self.recurrent_state_size,
            dtype=self.dtype,
            kernel_init=xavier_normal_init,
            name="rnn",
        )(recurrent_state.astype(self.dtype), feat)
        return new_h


class DV1WorldModel(nn.Module):
    """Encoder + continuous-latent RSSM + decoders + reward (+ optional
    continue) heads (reference: WorldModel container, agent.py:199-217 +
    RSSM, agent.py:64-197)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_input_channels: Sequence[int]
    mlp_input_dims: Sequence[int]
    image_size: Tuple[int, int]
    actions_dim: Sequence[int]
    stochastic_size: int = 30
    recurrent_state_size: int = 200
    transition_hidden_size: int = 200
    representation_hidden_size: int = 200
    encoder_cnn_channels_multiplier: int = 32
    encoder_mlp_layers: int = 4
    encoder_dense_units: int = 400
    decoder_cnn_channels_multiplier: int = 32
    decoder_mlp_layers: int = 4
    decoder_dense_units: int = 400
    reward_mlp_layers: int = 4
    reward_dense_units: int = 400
    continue_mlp_layers: int = 4
    continue_dense_units: int = 400
    use_continues: bool = False
    min_std: float = 0.1
    cnn_act: str = "relu"
    dense_act: str = "elu"
    dtype: Any = jnp.float32

    @property
    def latent_state_size(self) -> int:
        return self.stochastic_size + self.recurrent_state_size

    def setup(self) -> None:
        self.cnn_encoder = (
            DV2CNNEncoder(
                keys=self.cnn_keys,
                channels_multiplier=self.encoder_cnn_channels_multiplier,
                activation=self.cnn_act,
                layer_norm=False,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        self.mlp_encoder = (
            DV2MLPEncoder(
                keys=self.mlp_keys,
                mlp_layers=self.encoder_mlp_layers,
                dense_units=self.encoder_dense_units,
                activation=self.dense_act,
                layer_norm=False,
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.recurrent_model = DV1RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            activation=self.dense_act,
            dtype=self.dtype,
        )
        self.representation_model = MLP(
            hidden_sizes=[self.representation_hidden_size],
            output_dim=2 * self.stochastic_size,
            activation=self.dense_act,
            kernel_init=xavier_normal_init,
            output_kernel_init=xavier_normal_init,
            dtype=self.dtype,
        )
        self.transition_model = MLP(
            hidden_sizes=[self.transition_hidden_size],
            output_dim=2 * self.stochastic_size,
            activation=self.dense_act,
            kernel_init=xavier_normal_init,
            output_kernel_init=xavier_normal_init,
            dtype=self.dtype,
        )
        from sheeprl_tpu.algos.dreamer_v2.agent import cnn_encoder_output_dim

        enc_out = cnn_encoder_output_dim(self.image_size, self.encoder_cnn_channels_multiplier)
        self.cnn_decoder = (
            DV2CNNDecoder(
                keys=self.cnn_keys,
                output_channels=self.cnn_input_channels,
                channels_multiplier=self.decoder_cnn_channels_multiplier,
                cnn_encoder_output_dim=enc_out,
                image_size=self.image_size,
                activation=self.cnn_act,
                layer_norm=False,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        self.mlp_decoder = (
            DV2MLPDecoder(
                keys=self.mlp_keys,
                output_dims=self.mlp_input_dims,
                mlp_layers=self.decoder_mlp_layers,
                dense_units=self.decoder_dense_units,
                activation=self.dense_act,
                layer_norm=False,
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.reward_model = MLP(
            hidden_sizes=[self.reward_dense_units] * self.reward_mlp_layers,
            output_dim=1,
            activation=self.dense_act,
            kernel_init=xavier_normal_init,
            output_kernel_init=xavier_normal_init,
            dtype=self.dtype,
        )
        self.continue_model = (
            MLP(
                hidden_sizes=[self.continue_dense_units] * self.continue_mlp_layers,
                output_dim=1,
                activation=self.dense_act,
                kernel_init=xavier_normal_init,
                output_kernel_init=xavier_normal_init,
                dtype=self.dtype,
            )
            if self.use_continues
            else None
        )

    # --------------------------------------------------------------- encoder
    def embed_obs(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        return jnp.concatenate(outs, axis=-1) if len(outs) > 1 else outs[0]

    # ------------------------------------------------------------------ rssm
    def _representation(
        self, recurrent_state: jax.Array, embedded_obs: jax.Array, key: Optional[jax.Array]
    ) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
        return compute_stochastic_state_v1(
            self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], -1)),
            key,
            self.min_std,
        )

    def _transition(
        self, recurrent_out: jax.Array, key: Optional[jax.Array]
    ) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
        return compute_stochastic_state_v1(
            self.transition_model(recurrent_out), key, self.min_std
        )

    def dynamic(
        self,
        posterior: jax.Array,
        recurrent_state: jax.Array,
        action: jax.Array,
        embedded_obs: jax.Array,
        key: jax.Array,
    ):
        """One step of dynamic learning (reference: RSSM.dynamic,
        agent.py:97-134 — no is_first handling in DV1)."""
        k1, k2 = jax.random.split(key)
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        prior_mean_std, prior = self._transition(recurrent_state, k1)
        posterior_mean_std, posterior = self._representation(recurrent_state, embedded_obs, k2)
        return recurrent_state, posterior, prior, posterior_mean_std, prior_mean_std

    def imagination(
        self, stochastic_state: jax.Array, recurrent_state: jax.Array, actions: jax.Array, key: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """One-step latent imagination (reference: RSSM.imagination,
        agent.py:170-197)."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([stochastic_state, actions], -1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key)
        return imagined_prior, recurrent_state

    # ----------------------------------------------------------------- heads
    def decode(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent_states))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent_states))
        return out

    def reward(self, latent_states: jax.Array) -> jax.Array:
        return self.reward_model(latent_states)

    def continue_logits(self, latent_states: jax.Array) -> jax.Array:
        if self.continue_model is None:
            raise ValueError("use_continues is False: the continue model does not exist")
        return self.continue_model(latent_states)

    def __call__(self, obs: Dict[str, jax.Array], actions: jax.Array, key: jax.Array):
        """Init-only pass touching every submodule once."""
        embedded = self.embed_obs(obs)
        batch = embedded.shape[:-1]
        h0 = jnp.zeros((*batch, self.recurrent_state_size), self.dtype)
        z0 = jnp.zeros((*batch, self.stochastic_size), self.dtype)
        h, post, prior, _, _ = self.dynamic(z0, h0, actions, embedded, key)
        latent = jnp.concatenate([post, h], -1)
        out = (self.decode(latent), self.reward(latent))
        if self.continue_model is not None:
            out = out + (self.continue_logits(latent),)
        return out


@dataclass(frozen=True)
class DV1Agent:
    """Bundles modules + metadata; params live in the train state
    {world_model, actor, critic}."""

    world_model: DV1WorldModel
    actor: DV2Actor
    critic: Any  # MLP
    actor_spec: DV2ActorSpec
    actions_dim: Tuple[int, ...]
    is_continuous: bool

    def wm(self, params, *args, method: str):
        return self.world_model.apply(params, *args, method=getattr(DV1WorldModel, method))

    def critic_value(self, params, latent: jax.Array) -> jax.Array:
        return self.critic.apply(params, latent)

    # ---------------------------------------------------------------- player
    def init_player_state(self, wm_params, n_envs: int) -> Dict[str, jax.Array]:
        del wm_params
        return {
            "recurrent_state": jnp.zeros((n_envs, self.world_model.recurrent_state_size)),
            "stochastic_state": jnp.zeros((n_envs, self.world_model.stochastic_size)),
            "actions": jnp.zeros((n_envs, int(np.sum(self.actions_dim)))),
        }

    def reset_player_state(
        self, wm_params, state: Dict[str, jax.Array], reset_mask: jax.Array
    ) -> Dict[str, jax.Array]:
        m = reset_mask[..., None]
        return {k: (1 - m) * v for k, v in state.items()}

    def player_step(
        self,
        wm_params,
        actor_params,
        state: Dict[str, jax.Array],
        obs: Dict[str, jax.Array],
        key: jax.Array,
        greedy: bool = False,
        expl_amount: jax.Array = None,
    ):
        """One acting step (reference: PlayerDV1.get_actions/
        get_exploration_actions, agent.py:278-334). When `expl_amount` is
        given, exploration noise is added to the sampled actions."""
        k1, k2, k3 = jax.random.split(key, 3)
        embedded = self.wm(wm_params, obs, method="embed_obs")
        recurrent_state = self.world_model.apply(
            wm_params,
            jnp.concatenate([state["stochastic_state"], state["actions"]], -1),
            state["recurrent_state"],
            method=lambda wm, x, h: wm.recurrent_model(x, h),
        )
        _, stochastic_state = self.world_model.apply(
            wm_params, recurrent_state, embedded, k1, method=DV1WorldModel._representation
        )
        latent = jnp.concatenate([stochastic_state, recurrent_state], -1)
        pre_dist = self.actor.apply(actor_params, latent)
        actions, _ = dv2_actor_forward(pre_dist, self.actor_spec, k2, greedy)
        actions_cat = jnp.concatenate(actions, -1)
        if expl_amount is not None:
            actions_cat = add_exploration_noise(
                actions_cat, self.actor_spec, expl_amount, k3, self.actions_dim
            )
        if self.is_continuous:
            real_actions = actions_cat
        else:
            splits = np.cumsum(np.asarray(self.actions_dim))[:-1]
            real_actions = jnp.stack(
                [jnp.argmax(a, -1) for a in jnp.split(actions_cat, splits, -1)], -1
            )
        new_state = {
            "recurrent_state": recurrent_state,
            "stochastic_state": stochastic_state,
            "actions": actions_cat,
        }
        return actions_cat, real_actions, new_state


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    actor_state: Optional[Any] = None,
    critic_state: Optional[Any] = None,
) -> Tuple[DV1Agent, Dict[str, Any]]:
    """Construct modules + initial (or restored) params
    (reference: build_agent, agent.py:337-547)."""
    dtype = runtime.precision.compute_dtype
    distribution = str(cfg.distribution.get("type", "auto")).lower()
    if distribution not in ("auto", "normal", "tanh_normal", "discrete", "trunc_normal"):
        raise ValueError(
            "The distribution must be on of: `auto`, `discrete`, `normal`, `tanh_normal` and `trunc_normal`. "
            f"Found: {distribution}"
        )
    if distribution == "discrete" and is_continuous:
        raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
    if distribution == "auto":
        distribution = "trunc_normal" if is_continuous else "discrete"

    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    wm = DV1WorldModel(
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_input_channels=tuple(int(obs_space[k].shape[-1]) for k in cnn_keys),
        mlp_input_dims=tuple(int(obs_space[k].shape[0]) for k in mlp_keys),
        image_size=tuple(obs_space[cnn_keys[0]].shape[:2]) if cnn_keys else (64, 64),
        actions_dim=tuple(actions_dim),
        stochastic_size=wm_cfg.stochastic_size,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        transition_hidden_size=wm_cfg.transition_model.hidden_size,
        representation_hidden_size=wm_cfg.representation_model.hidden_size,
        encoder_cnn_channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        encoder_mlp_layers=wm_cfg.encoder.mlp_layers,
        encoder_dense_units=wm_cfg.encoder.dense_units,
        decoder_cnn_channels_multiplier=wm_cfg.observation_model.cnn_channels_multiplier,
        decoder_mlp_layers=wm_cfg.observation_model.mlp_layers,
        decoder_dense_units=wm_cfg.observation_model.dense_units,
        reward_mlp_layers=wm_cfg.reward_model.mlp_layers,
        reward_dense_units=wm_cfg.reward_model.dense_units,
        continue_mlp_layers=wm_cfg.discount_model.mlp_layers,
        continue_dense_units=wm_cfg.discount_model.dense_units,
        use_continues=bool(wm_cfg.use_continues),
        min_std=float(wm_cfg.min_std),
        cnn_act="relu",
        dense_act="elu",
        dtype=dtype,
    )
    actor = DV2Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        activation="elu",
        layer_norm=False,
        dtype=dtype,
    )
    critic = MLP(
        hidden_sizes=[cfg.algo.critic.dense_units] * cfg.algo.critic.mlp_layers,
        output_dim=1,
        activation="elu",
        kernel_init=xavier_normal_init,
        output_kernel_init=xavier_normal_init,
        dtype=dtype,
    )
    spec = DV2ActorSpec(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=is_continuous,
        distribution=distribution,
        init_std=cfg.algo.actor.init_std,
        min_std=cfg.algo.actor.min_std,
        expl_amount=float(cfg.algo.actor.get("expl_amount", 0.3)),
        expl_decay=float(cfg.algo.actor.get("expl_decay", 0.0)),
        expl_min=float(cfg.algo.actor.get("expl_min", 0.0)),
    )
    agent = DV1Agent(
        world_model=wm,
        actor=actor,
        critic=critic,
        actor_spec=spec,
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=is_continuous,
    )

    k_wm, k_actor, k_critic, k_call = jax.random.split(runtime.root_key, 4)
    n = 1
    dummy_obs = {
        k: jnp.zeros((n, *obs_space[k].shape), jnp.float32) for k in cnn_keys + mlp_keys
    }
    dummy_actions = jnp.zeros((n, int(np.sum(actions_dim))), jnp.float32)
    latent_size = wm.latent_state_size

    if world_model_state is not None:
        wm_params = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    else:
        wm_params = wm.init({"params": k_wm, "sample": k_call}, dummy_obs, dummy_actions, k_call)
    actor_params = (
        jax.tree_util.tree_map(jnp.asarray, actor_state)
        if actor_state is not None
        else actor.init(k_actor, jnp.zeros((n, latent_size), jnp.float32))
    )
    critic_params = (
        jax.tree_util.tree_map(jnp.asarray, critic_state)
        if critic_state is not None
        else critic.init(k_critic, jnp.zeros((n, latent_size), jnp.float32))
    )
    state = {"world_model": wm_params, "actor": actor_params, "critic": critic_params}
    return agent, state
