"""Algorithm and evaluation registries.

Decorator-driven maps from algorithm name to its entrypoint module, matching
the reference's registry contract (sheeprl/utils/registry.py:11-108): each
algorithm module registers a ``main(runtime, cfg)`` entrypoint and,
separately, an evaluation function. The ``decoupled`` flag marks algorithms
whose training loop runs a player/trainer process split.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

algorithm_registry: Dict[str, "AlgorithmEntry"] = {}
evaluation_registry: Dict[str, "EvaluationEntry"] = {}


@dataclass
class AlgorithmEntry:
    name: str
    module: str
    entrypoint: Callable[..., Any]
    decoupled: bool = False


@dataclass
class EvaluationEntry:
    name: str
    module: str
    entrypoint: Callable[..., Any]


def register_algorithm(name: Optional[str] = None, decoupled: bool = False):
    def decorator(fn: Callable[..., Any]):
        # Default name = module file basename, exactly like the reference
        # (sheeprl/utils/registry.py:21): sheeprl_tpu.algos.ppo.ppo_decoupled
        # registers as "ppo_decoupled", avoiding sibling collisions.
        algo_name = name or fn.__module__.split(".")[-1]
        if algo_name in algorithm_registry and algorithm_registry[algo_name].module != fn.__module__:
            raise ValueError(f"Algorithm '{algo_name}' already registered by {algorithm_registry[algo_name].module}")
        algorithm_registry[algo_name] = AlgorithmEntry(algo_name, fn.__module__, fn, decoupled)
        return fn

    return decorator


def register_evaluation(algorithms):
    names = [algorithms] if isinstance(algorithms, str) else list(algorithms)

    def decorator(fn: Callable[..., Any]):
        for algo_name in names:
            evaluation_registry[algo_name] = EvaluationEntry(algo_name, fn.__module__, fn)
        return fn

    return decorator
