"""Disk-backed numpy arrays for replay buffers.

Behavioral parity with the reference's MemmapArray (sheeprl/utils/memmap.py:22-270):
lazily-opened ``np.memmap`` storage with explicit file ownership (the owner
deletes the backing file on collection), pickling that reopens the mapping in
the child process without transferring ownership (worker-safe), and ndarray
duck-typing so buffer code can treat it as a plain array.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Iterator, Optional, Tuple

import numpy as np
from numpy.typing import DTypeLike

_VALID_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")

# Thread-local flag set by ownership_transfer_scope(): only pickles taken
# inside the scope strip file ownership from the SOURCE object.
_TRANSFER_SCOPE = threading.local()


@contextmanager
def ownership_transfer_scope() -> Iterator[None]:
    """Mark the enclosed pickling as a durable persistence path.

    Pickling a :class:`MemmapArray` inside this scope relinquishes the
    *source* object's file ownership: the pickle is a durable external
    reference to the backing file (a buffer inside a checkpoint), and
    deleting the file when the source is collected would strand it — a
    resumed run would open a deleted file. The checkpoint save path
    (``utils/checkpoint.py``) wraps its aux pickle in this scope.

    Outside the scope, pickling still produces a non-owning copy (worker
    processes never delete the file) but the source KEEPS ownership: a
    transient pickle — shipping the buffer to an env worker, a debug
    ``copy.deepcopy`` probe — must not silently leak the backing file's
    lifetime to the run directory.
    """
    prev = getattr(_TRANSFER_SCOPE, "active", False)
    _TRANSFER_SCOPE.active = True
    try:
        yield
    finally:
        _TRANSFER_SCOPE.active = prev


class MemmapArray:
    def __init__(
        self,
        filename: str | os.PathLike,
        dtype: DTypeLike,
        shape: Tuple[int, ...],
        mode: str = "r+",
    ):
        if mode not in _VALID_MODES:
            raise ValueError(f"Accepted values for mode are {_VALID_MODES}, got '{mode}'")
        self._filename = Path(filename).absolute()
        self._dtype = np.dtype(dtype)
        self._shape = tuple(shape)
        self._mode = mode
        self._array: Optional[np.memmap] = None
        self._has_ownership = True
        self._filename.parent.mkdir(parents=True, exist_ok=True)
        if not self._filename.exists() or os.path.getsize(self._filename) != self._dtype.itemsize * int(
            np.prod(self._shape)
        ):
            # First creation must allocate the file ("w+"); subsequent opens
            # honor the requested mode.
            np.memmap(self._filename, dtype=self._dtype, shape=self._shape, mode="w+").flush()

    # ----------------------------------------------------------- properties
    @property
    def filename(self) -> Path:
        return self._filename

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    @has_ownership.setter
    def has_ownership(self, value: bool) -> None:
        self._has_ownership = bool(value)

    @property
    def array(self) -> np.memmap:
        if self._array is None:
            self._array = np.memmap(self._filename, dtype=self._dtype, shape=self._shape, mode=self._mode)
        return self._array

    # ------------------------------------------------------------- creation
    @classmethod
    def from_array(
        cls,
        array: "np.ndarray | MemmapArray",
        filename: str | os.PathLike,
        mode: str = "r+",
    ) -> "MemmapArray":
        if isinstance(array, MemmapArray):
            source = array.array
        else:
            source = np.asarray(array)
        out = cls(filename=filename, dtype=source.dtype, shape=source.shape, mode=mode)
        same_file = isinstance(array, MemmapArray) and Path(filename).absolute() == array.filename
        if not same_file:
            out.array[:] = source
            out.array.flush()
        else:
            # Pointing at the same backing file: become a non-owning view so
            # two collectors don't both try to delete it.
            out._has_ownership = False
        return out

    # ------------------------------------------------------------ lifecycle
    def __del__(self) -> None:
        # Runs during interpreter shutdown too, when module globals (os,
        # pathlib internals) may already be torn down — never raise here.
        try:
            if getattr(self, "_has_ownership", False) and getattr(self, "_filename", None) is not None:
                array = self._array
                if array is not None:
                    array.flush()
                    del array
                self._array = None
                self._filename.unlink(missing_ok=True)
        except Exception:
            pass

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        state["_array"] = None
        # Unpickled copies (e.g. in worker processes) never own the file.
        state["_has_ownership"] = False
        # Inside ownership_transfer_scope() a durable external reference to
        # the backing file now exists (a buffer-in-checkpoint): unlinking it
        # when THIS object is collected would strand that reference — a
        # resumed run would open a deleted file (observed: FileNotFoundError
        # on the first post-resume add). Relinquish deletion; the file's
        # lifetime now follows the run directory, not this process. Outside
        # the scope the pickle is transient (a worker ship-over) and the
        # source keeps ownership — stripping it here used to leak every
        # memmap file a worker ever saw.
        if getattr(_TRANSFER_SCOPE, "active", False):
            self._has_ownership = False
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    def __deepcopy__(self, memo: dict) -> "MemmapArray":
        # Without this, deepcopy falls back to __getstate__ and its
        # pickling side effect would strip ownership from the SOURCE for a
        # mere in-process copy. A deepcopy is a non-owning view (two
        # owners would double-delete); the source keeps its ownership.
        clone = type(self)(self._filename, self._dtype, self._shape, self._mode)
        clone._has_ownership = False
        memo[id(self)] = clone
        return clone

    def __copy__(self) -> "MemmapArray":
        # Same rationale as __deepcopy__: copy.copy() would otherwise route
        # through __getstate__, whose pickling side effect strips ownership
        # from the SOURCE for a mere in-process shallow copy.
        clone = type(self)(self._filename, self._dtype, self._shape, self._mode)
        clone._has_ownership = False
        return clone

    # ---------------------------------------------------------- array-like
    def __array__(self, dtype: DTypeLike = None) -> np.ndarray:
        arr = self.array
        return np.asarray(arr, dtype=dtype) if dtype is not None else arr

    def __getitem__(self, idx: Any) -> np.ndarray:
        return self.array[idx]

    def __setitem__(self, idx: Any, value: Any) -> None:
        self.array[idx] = value

    def __getattr__(self, attr: str) -> Any:
        # Delegate ndarray API (ndim, size, reshape, ...) to the mapping.
        if attr.startswith("_"):
            raise AttributeError(attr)
        return getattr(self.array, attr)

    def __len__(self) -> int:
        return self._shape[0]

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, file={self._filename}, owner={self._has_ownership})"
