from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    get_array,
)
from sheeprl_tpu.data.memmap import MemmapArray, ownership_transfer_scope

__all__ = [
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ReplayBuffer",
    "SequentialReplayBuffer",
    "get_array",
    "MemmapArray",
    "ownership_transfer_scope",
]
