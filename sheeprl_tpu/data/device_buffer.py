"""Device-resident replay ring: the replay buffer as a pytree of HBM arrays.

PROFILE.md's round-3 roofline left the host-side data path as the last
measured overhead in the DV3 step: a memcpy-bound numpy gather plus a
~12 MB host→device transfer *per gradient step*. The T5X-style answer is to
keep the ring on-device and sample it inside the train jit, so the host
never touches the hot path:

- :class:`DeviceReplayRing` mirrors the host replay ring as a dict of
  ``(capacity, n_envs, *feature)`` arrays living in HBM. Rollout rows are
  *staged* on the host (cheap numpy copies) and shipped once per train
  interval by :meth:`flush` — a single donated jitted scatter, not one
  transfer per gradient step.
- :meth:`make_sample_fn` returns a **pure function** ``sample(state, key)``
  that draws uniform sequence starts with the JAX PRNG entirely inside the
  caller's jit, reproducing ``SequentialReplayBuffer``'s valid-start
  semantics (the write head never appears inside a sampled window).
- Capacity accounting up front: when the ring would not fit the HBM budget
  the ring deactivates itself and the train loop falls back to the existing
  host buffer + ``ReplayInfeed`` path.

The host replay buffer stays authoritative for checkpointing — ring writes
are additive, so resume just replays the host ring into HBM via
:meth:`load_host_buffer`. Nothing here is pickled.

Valid-start math (shared by the in-jit sampler and the tests): with
per-env write position ``pos``, per-env total rows written ``added``,
ring ``capacity`` and window ``span``::

    full    = added >= capacity
    n_valid = full ? capacity - span + 1 : max(added - span + 1, 1)
    offset  = full ? pos : 0
    start   = (offset + uniform_int(0, n_valid)) % capacity

which enumerates exactly the starts ``SequentialReplayBuffer.sample``
allows: the oldest valid start is the write head itself once the ring has
wrapped (the head is the oldest row), and windows never straddle the seam
between the newest and the oldest row.
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import mesh as mesh_lib
from sheeprl_tpu.telemetry import tracer as tracer_mod

__all__ = ["DeviceReplayRing", "next_power_of_two"]


def next_power_of_two(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (max(int(n), 1) - 1).bit_length()


def _feature_shape(shape: Sequence[int]) -> Tuple[int, ...]:
    """Feature dims of a ``[T, E, *feature]`` rollout array."""
    return tuple(int(s) for s in shape[2:])


class DeviceReplayRing:
    """A replay ring held in device memory as ``{key: (capacity, n_envs, *f)}``.

    Host-side staging + one donated jitted write per :meth:`flush`; sampling
    is a pure function over :attr:`state` built by :meth:`make_sample_fn`
    and meant to be closed over by the caller's train jit.

    The ring is *additive*: the host buffer keeps receiving the same rows
    and remains the checkpoint source of truth. ``capacity`` is the per-env
    ring length (matching the host per-env sub-buffer size).

    With ``mesh`` given (and ``n_envs`` divisible by its `data` axis) the
    ring is **sharded across the mesh**: storage lives as
    ``[capacity, n_envs/data, *f]`` per shard (env columns split over
    `data`, no full-ring replication), :meth:`flush` stages rows onto the
    shard that owns those envs, and the in-jit writer/sampler run SPMD.
    Sampling keeps *global* uniform semantics — indices are computed from
    replicated pos/added and the same PRNG bits on every topology (under
    ``jax_threefry_partitionable``), so a sharded ring draws the identical
    batch a single-device ring would; the sampled batch is then constrained
    back onto the `data` axis so each shard trains on the rows it owns.
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        cnn_keys: Sequence[str] = (),
        obs_keys: Sequence[str] = ("observations",),
        hbm_fraction: float = 0.4,
        hbm_budget_bytes: Optional[int] = None,
        device: Any = None,
        mesh: Any = None,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"DeviceReplayRing capacity must be >= 1, got {capacity}")
        if n_envs < 1:
            raise ValueError(f"DeviceReplayRing n_envs must be >= 1, got {n_envs}")
        self.capacity = int(capacity)
        self.n_envs = int(n_envs)
        self.cnn_keys = tuple(cnn_keys)
        self.obs_keys = tuple(obs_keys)
        self.hbm_fraction = float(hbm_fraction)
        self.hbm_budget_bytes = hbm_budget_bytes if hbm_budget_bytes is None else int(hbm_budget_bytes)
        self._device = device
        self._mesh = None
        if mesh is not None:
            data_size = int(mesh.shape[mesh_lib.DATA_AXIS])
            if self.n_envs % data_size == 0:
                self._mesh = mesh
            else:
                warnings.warn(
                    f"DeviceReplayRing: n_envs {self.n_envs} not divisible by the "
                    f"`{mesh_lib.DATA_AXIS}` mesh axis ({data_size}); the ring stays "
                    "unsharded (single-device placement)."
                )
        # Ring state (allocated lazily on the first add, when key shapes and
        # dtypes are known).
        self._specs: Optional[Dict[str, Tuple[Tuple[int, ...], np.dtype]]] = None
        self._data: Optional[Dict[str, jax.Array]] = None
        self._pos: Optional[jax.Array] = None
        self._added: Optional[jax.Array] = None
        # Host-side mirrors of pos/added so readiness checks never touch the
        # device (GL002: no per-iteration host sync).
        self._host_pos = np.zeros(self.n_envs, dtype=np.int64)
        self._host_added = np.zeros(self.n_envs, dtype=np.int64)
        # Staged rows awaiting flush: parallel lists of (mask[E], {k: row[E,*f]}).
        self._staged_masks: List[np.ndarray] = []
        self._staged_rows: List[Dict[str, np.ndarray]] = []
        self._write_fn = None
        # active=False -> the ring declined its allocation (HBM budget) and
        # every method is a no-op; callers use the host path instead.
        self.active = True
        self.inactive_reason: Optional[str] = None

    # ------------------------------------------------------------ capacity
    def _budget_bytes(self) -> Optional[int]:
        """The HBM byte budget, or None when unknown (no accounting)."""
        if self.hbm_budget_bytes is not None:
            return self.hbm_budget_bytes
        device = self._device
        if device is None:
            devices = jax.local_devices()
            device = devices[0] if devices else None
        if device is None:
            return None
        stats = getattr(device, "memory_stats", None)
        if stats is None:
            return None
        try:
            limit = (stats() or {}).get("bytes_limit")
        except Exception:  # memory_stats unsupported on this backend
            return None
        if limit is None:
            return None
        return int(int(limit) * self.hbm_fraction)

    def ring_nbytes(self) -> int:
        """Total ring bytes for the recorded key specs (0 before first add)."""
        if self._specs is None:
            return 0
        total = 0
        for feature, dtype in self._specs.values():
            total += self.capacity * self.n_envs * int(np.prod(feature, dtype=np.int64)) * dtype.itemsize
        return total

    def _deactivate(self, reason: str) -> None:
        self.active = False
        self.inactive_reason = reason
        self._staged_masks.clear()
        self._staged_rows.clear()
        self._data = None
        warnings.warn(f"DeviceReplayRing disabled, falling back to the host buffer path: {reason}")

    def _allocate(self) -> None:
        needed = self.ring_nbytes()
        budget = self._budget_bytes()
        if budget is not None and needed > budget:
            self._deactivate(
                f"ring needs {needed / 2**20:.1f} MiB but the HBM budget is {budget / 2**20:.1f} MiB"
            )
            return
        shardings = self.state_shardings()
        data: Dict[str, jax.Array] = {}
        for key, (feature, dtype) in self._specs.items():
            shape = (self.capacity, self.n_envs) + feature
            if shardings is not None:
                # Sharded allocation: each shard materializes only its own
                # env columns — no full-ring replication across the mesh.
                data[key] = jnp.zeros(shape, dtype=dtype, device=shardings["data"])
            else:
                data[key] = jnp.zeros(shape, dtype=dtype)
        self._data = data
        env_sharding = None if shardings is None else shardings["pos"]
        if env_sharding is not None:
            self._pos = jnp.zeros(self.n_envs, dtype=jnp.int32, device=env_sharding)
            self._added = jnp.zeros(self.n_envs, dtype=jnp.int32, device=env_sharding)
        else:
            self._pos = jnp.zeros(self.n_envs, dtype=jnp.int32)
            self._added = jnp.zeros(self.n_envs, dtype=jnp.int32)
        tracer_mod.current().set_gauge("replay_ring_bytes", float(needed))

    # ------------------------------------------------------------- staging
    def add(self, data: Dict[str, Any], env_idxes: Optional[Sequence[int]] = None) -> None:
        """Stage ``[T, E', *f]`` rows for the given env columns (all when
        ``env_idxes`` is None). Values are **copied** — callers are free to
        mutate ``data`` in place afterwards (the train loops do)."""
        if not self.active:
            return
        if env_idxes is None:
            env_idxes = range(self.n_envs)
        env_idxes = [int(e) for e in env_idxes]
        arrays = {key: np.asarray(value) for key, value in data.items()}
        n_steps = int(next(iter(arrays.values())).shape[0])
        if self._specs is None:
            # First add fixes the key set, feature shapes and dtypes; the
            # HBM budget check happens here so a too-big ring deactivates
            # before any staging cost is paid.
            self._specs = {
                key: (_feature_shape(value.shape), np.dtype(value.dtype))
                for key, value in arrays.items()
            }
            needed = self.ring_nbytes()
            budget = self._budget_bytes()
            if budget is not None and needed > budget:
                self._deactivate(
                    f"ring needs {needed / 2**20:.1f} MiB but the HBM budget is {budget / 2**20:.1f} MiB"
                )
                return
        for t in range(n_steps):
            mask = np.zeros(self.n_envs, dtype=bool)
            mask[env_idxes] = True
            row: Dict[str, np.ndarray] = {}
            for key, (feature, dtype) in self._specs.items():
                full_row = np.zeros((self.n_envs,) + feature, dtype=dtype)
                value = arrays.get(key)
                if value is not None:
                    # Keys absent from this add (e.g. sparse reset rows)
                    # keep their natural zero, matching what the loops put
                    # in reset rows explicitly.
                    full_row[env_idxes] = value[t]
                row[key] = full_row
            self._staged_masks.append(mask)
            self._staged_rows.append(row)
        self._host_pos[env_idxes] = (self._host_pos[env_idxes] + n_steps) % self.capacity
        self._host_added[env_idxes] = np.minimum(self._host_added[env_idxes] + n_steps, self.capacity)

    def amend_last(self, env_idx: int, values: Dict[str, Any]) -> None:
        """Patch the newest row written for one env (staged when possible,
        an eager device update otherwise). Used by the restart-on-exception
        path to flip terminal flags on the already-added row."""
        if not self.active:
            return
        env_idx = int(env_idx)
        for mask, row in zip(reversed(self._staged_masks), reversed(self._staged_rows)):
            if mask[env_idx]:
                for key, value in values.items():
                    if key in row:
                        row[key][env_idx] = np.asarray(value).reshape(row[key][env_idx].shape)
                return
        if self._data is None or self._host_added[env_idx] == 0:
            return
        t = int((self._host_pos[env_idx] - 1) % self.capacity)
        for key, value in values.items():
            if key in self._data:
                patch = jnp.asarray(np.asarray(value).reshape(self._data[key].shape[2:]))
                self._data[key] = self._data[key].at[t, env_idx].set(patch.astype(self._data[key].dtype))

    # ----------------------------------------------------------- sharding
    @property
    def mesh(self) -> Any:
        """The mesh the ring is sharded over, or None when unsharded."""
        return self._mesh

    def state_shardings(self) -> Optional[Dict[str, Any]]:
        """Sharding pytree-prefix matching :attr:`state` when the ring is
        mesh-sharded (None otherwise): ring storage is ``P(None, data)``
        (env columns over `data`), pos/added ``P(data)``. The ``data`` entry
        is a single sharding applied to every ring key (jit prefix
        semantics), so this works before the specs are known too — feed it
        to the fused train jit's ``in_shardings``/``out_shardings`` so the
        carried ring state keeps its layout across supersteps."""
        if self._mesh is None:
            return None
        row = NamedSharding(self._mesh, P(None, mesh_lib.DATA_AXIS))
        env = NamedSharding(self._mesh, P(mesh_lib.DATA_AXIS))
        return {"data": row, "pos": env, "added": env}

    # --------------------------------------------------------------- write
    def _build_write_fn(self):
        capacity = self.capacity
        n_envs = self.n_envs
        env_ids = jnp.arange(n_envs)

        @partial(jax.jit, donate_argnums=(0,))
        def write(data, pos, added, rows, mask, shift):
            # mask: [S, E] bool; rows: {k: [S, E, *f]}. Per-env cumulative
            # write count turns the staged order into ring targets; masked-out
            # slots are sent out of bounds and dropped by the scatter.
            # shift: [E] rows the host dropped when trimming an oversized
            # flush — they still advance the write head, keeping the device
            # pos in lockstep with the host mirror.
            pos = (pos + shift) % capacity
            counts = jnp.cumsum(mask.astype(jnp.int32), axis=0)  # [S, E]
            t_idx = jnp.where(mask, (pos[None, :] + counts - 1) % capacity, capacity)
            e_idx = jnp.broadcast_to(env_ids[None, :], t_idx.shape)
            new_data = {
                key: value.at[t_idx, e_idx].set(rows[key].astype(value.dtype), mode="drop")
                for key, value in data.items()
            }
            new_pos = (pos + counts[-1]) % capacity
            new_added = jnp.minimum(added + shift + counts[-1], capacity)
            return new_data, new_pos, new_added

        return write

    def flush(self) -> bool:
        """Ship every staged row to the device in ONE donated jitted write.

        Returns True when a write happened. The staged step count is padded
        to the next power of two (extra rows fully masked out) so the write
        kernel recompiles at most log2(max_steps) times.
        """
        if not self.active or not self._staged_rows:
            return False
        if self._data is None:
            self._allocate()
            if not self.active:
                return False
        n_staged = len(self._staged_rows)
        shift = np.zeros(self.n_envs, dtype=np.int32)
        if n_staged > self.capacity:
            # Only the last `capacity` masked rows per env can survive; drop
            # older ones on the host so ring targets stay collision-free.
            # The dropped rows still advance the write head (shift), keeping
            # the device pos equal to the host mirror's.
            masks = np.stack(self._staged_masks, axis=0)
            seen_from_end = np.cumsum(masks[::-1].astype(np.int64), axis=0)[::-1]
            keep = masks & (seen_from_end <= self.capacity)
            shift = (masks.sum(axis=0) - keep.sum(axis=0)).astype(np.int32)
            self._staged_masks = [keep[t] for t in range(n_staged)]
        padded = next_power_of_two(n_staged)
        mask = np.zeros((padded, self.n_envs), dtype=bool)
        mask[:n_staged] = np.stack(self._staged_masks, axis=0)
        rows: Dict[str, np.ndarray] = {}
        for key in self._staged_rows[0]:
            stacked = np.stack([row[key] for row in self._staged_rows], axis=0)
            if padded > n_staged:
                pad = np.zeros((padded - n_staged,) + stacked.shape[1:], dtype=stacked.dtype)
                stacked = np.concatenate([stacked, pad], axis=0)
            rows[key] = stacked
        self._staged_masks.clear()
        self._staged_rows.clear()
        if self._write_fn is None:
            self._write_fn = self._build_write_fn()
        nbytes = int(sum(value.nbytes for value in rows.values()) + mask.nbytes)
        trc = tracer_mod.current()
        if self._mesh is not None:
            # Per-shard staging: each staged row lands directly on the shard
            # that owns its env columns (env dim 1 split over `data`), so the
            # donated SPMD write scatters locally — no full-row replication.
            rows = mesh_lib.shard_batch(rows, self._mesh, axis=1)
            mask = mesh_lib.shard_batch(mask, self._mesh, axis=1)
            shift = mesh_lib.shard_batch(shift, self._mesh, axis=0)
        with trc.span("transfer/ring_write", "transfer", steps=n_staged, bytes=nbytes):
            self._data, self._pos, self._added = self._write_fn(
                self._data, self._pos, self._added, rows, mask, shift
            )
        trc.count("host_to_device_calls", 1)
        trc.count("host_to_device_bytes", nbytes)
        trc.count("ring_write_rows", int(mask.sum()))
        return True

    # ------------------------------------------------- fused-lane interface
    def allocate(self, specs: Dict[str, Tuple[Sequence[int], Any]]) -> None:
        """Eagerly allocate the ring from explicit per-key feature specs.

        The host-interaction lane allocates lazily on the first ``add`` (the
        staged row fixes shapes/dtypes); the fused lane writes rows *inside*
        the superstep jit and never stages, so the ring must exist — with
        the HBM budget check already passed — before the first dispatch.
        ``specs`` maps key -> (feature_shape, dtype). No-op when already
        allocated with identical specs; mismatched re-allocation raises.
        """
        if not self.active:
            return
        normalized = {
            key: (tuple(int(s) for s in feature), np.dtype(dtype))
            for key, (feature, dtype) in specs.items()
        }
        if self._specs is not None:
            if self._specs != normalized:
                raise ValueError(
                    f"DeviceReplayRing.allocate specs mismatch: ring holds {self._specs}, "
                    f"caller wants {normalized}"
                )
            if self._data is not None:
                return
        self._specs = normalized
        self._allocate()

    def make_step_write_fn(self) -> Callable[[Dict[str, Any], Dict[str, jax.Array], jax.Array], Dict[str, Any]]:
        """Build the pure in-jit per-step writer ``write(state, row, mask)``.

        The fused rollout scan appends one ``[E, *f]`` row per env step
        directly into the ring pytree carried through the scan — zero host
        staging, zero transfers. ``mask`` ([E] bool) gates which env
        columns advance (dreamer's sparse reset rows); masked-out columns
        are scattered out of bounds and dropped. Semantics match one
        staged ``add`` + ``flush`` per masked column, so the host mirror
        stays in lockstep via :meth:`advance_host`.

        The writer derives its env width from the traced ``state`` (not the
        ring's global ``n_envs``), so the same function works unchanged
        inside a ``shard_map`` over `data`, where each shard carries only
        its own ``n_envs/data`` env columns.
        """
        capacity = self.capacity

        def write(state: Dict[str, Any], row: Dict[str, jax.Array], mask: jax.Array) -> Dict[str, Any]:
            pos = state["pos"]
            added = state["added"]
            env_ids = jnp.arange(pos.shape[0])  # local width under shard_map
            inc = mask.astype(jnp.int32)
            t_idx = jnp.where(mask, pos, capacity)  # out-of-bounds -> dropped
            data = {
                key: value.at[t_idx, env_ids].set(
                    row[key].astype(value.dtype), mode="drop"
                )
                for key, value in state["data"].items()
            }
            return {
                "data": data,
                "pos": (pos + inc) % capacity,
                "added": jnp.minimum(added + inc, capacity),
            }

        return write

    def adopt_state(self, state: Dict[str, Any], steps_written: Any = 0) -> None:
        """Adopt the ring pytree a fused superstep returned (donated in, new
        buffers out) and advance the host pos/added mirrors by the rows the
        superstep wrote per env — pure host arithmetic, no device sync."""
        if not self.active:
            return
        self._data = state["data"]
        self._pos = state["pos"]
        self._added = state["added"]
        steps = np.asarray(steps_written, dtype=np.int64)
        self._host_pos = (self._host_pos + steps) % self.capacity
        self._host_added = np.minimum(self._host_added + steps, self.capacity)

    # ------------------------------------------------------------ sampling
    @property
    def state(self) -> Dict[str, Any]:
        """The device-resident ring as a pytree: pass this into the train
        jit; :meth:`make_sample_fn`'s pure function consumes it."""
        if self._data is None:
            raise RuntimeError("DeviceReplayRing.state read before the first flush allocated the ring")
        return {"data": self._data, "pos": self._pos, "added": self._added}

    def ready(self, span: int) -> bool:
        """True when every env column has at least ``span`` rows *flushed*,
        so the in-jit sampler cannot window into unwritten rows. Pure host
        arithmetic — no device sync."""
        if not self.active or self._data is None:
            return False
        return bool(self._host_added.min() >= max(int(span), 1)) and span <= self.capacity

    def make_sample_fn(
        self,
        batch_size: int,
        sequence_length: int = 1,
        sample_next_obs: bool = False,
        time_major: bool = False,
    ) -> Callable[[Dict[str, Any], jax.Array], Dict[str, jax.Array]]:
        """Build the pure in-jit sampler ``sample(state, key) -> batch``.

        Uniform env choice then uniform valid sequence start per sample —
        ``SequentialReplayBuffer`` semantics (one env per sequence, windows
        never cross the write head). Output is ``[B, *f]`` when
        ``sequence_length == 1`` and ``time_major`` is False, else
        ``[L, B, *f]`` (time-major) or ``[B, L, *f]``. Non-CNN keys are cast
        to float32 in-jit (the CNN keys keep their storage dtype for the
        train step's own ``/255`` normalisation). With ``sample_next_obs``
        the window is one longer and each obs key ``k`` gains ``next_k``.
        """
        capacity = self.capacity
        cnn_keys = frozenset(self.cnn_keys)
        obs_keys = tuple(self.obs_keys)
        span = int(sequence_length) + int(bool(sample_next_obs))
        if span > capacity:
            raise ValueError(
                f"sequence window {span} exceeds DeviceReplayRing capacity {capacity}"
            )
        batch_size = int(batch_size)
        sequence_length = int(sequence_length)
        batch_constraint = None
        if self._mesh is not None and int(self._mesh.shape[mesh_lib.DATA_AXIS]) > 1:
            if batch_size % int(self._mesh.shape[mesh_lib.DATA_AXIS]) == 0:
                # Sampled rows re-land on the shard that trains on them: the
                # batch dim splits over `data` (dim 1 when time-major).
                spec = P(None, mesh_lib.DATA_AXIS) if time_major else P(mesh_lib.DATA_AXIS)
                batch_constraint = NamedSharding(self._mesh, spec)

        def _cast(key: str, value: jax.Array) -> jax.Array:
            return value if key in cnn_keys else value.astype(jnp.float32)

        def _shape(value: jax.Array) -> jax.Array:
            # value: [B, L(+1) sliced to L, *f] -> requested layout.
            if sequence_length == 1 and not time_major:
                return value[:, 0]
            if time_major:
                return jnp.swapaxes(value, 0, 1)
            return value

        def sample(state: Dict[str, Any], key: jax.Array) -> Dict[str, jax.Array]:
            pos = state["pos"]
            added = state["added"]
            # Env width from the traced state, not the ring's global n_envs:
            # the sampler stays correct if the caller hands it a sub-ring.
            num_envs = pos.shape[0]
            k_env, k_start = jax.random.split(key)
            env_idx = jax.random.randint(k_env, (batch_size,), 0, num_envs)
            full = added >= capacity
            n_valid = jnp.where(
                full,
                capacity - span + 1,
                jnp.maximum(added - span + 1, 1),
            )
            offset = jnp.where(full, pos, 0)
            r = jax.random.randint(k_start, (batch_size,), 0, n_valid[env_idx])
            start = (offset[env_idx] + r) % capacity
            t_idx = (start[:, None] + jnp.arange(span)) % capacity  # [B, span]
            batch: Dict[str, jax.Array] = {}
            for name, ring in state["data"].items():
                window = ring[t_idx, env_idx[:, None]]  # [B, span, *f]
                batch[name] = _shape(_cast(name, window[:, :sequence_length]))
                if sample_next_obs and name in obs_keys:
                    batch[f"next_{name}"] = _shape(_cast(name, window[:, 1:]))
            if batch_constraint is not None:
                batch = {
                    name: jax.lax.with_sharding_constraint(value, batch_constraint)
                    for name, value in batch.items()
                }
            return batch

        return sample

    # ------------------------------------------------------------- resume
    def load_host_buffer(self, rb: Any) -> None:
        """Stage the host buffer's current contents chronologically (oldest first)
        so a resumed run samples its checkpointed history on-device.

        Understands ``EnvIndependentReplayBuffer`` (per-env sub-buffers) and
        flat ``ReplayBuffer``/``SequentialReplayBuffer``; anything else
        (episode buffers) deactivates the ring with a warning.
        """
        if not self.active:
            return
        sub_buffers = getattr(rb, "buffer", None)
        if sub_buffers is not None and isinstance(sub_buffers, (list, tuple)):
            for env_idx, sub in enumerate(sub_buffers):
                self._load_flat(sub, [env_idx])
            return
        if hasattr(rb, "_pos") and hasattr(rb, "full"):
            self._load_flat(rb, list(range(self.n_envs)))
            return
        self._deactivate(f"cannot mirror a {type(rb).__name__} into the device ring")

    def _load_flat(self, rb: Any, env_idxes: List[int]) -> None:
        if getattr(rb, "empty", True):
            return
        size = int(rb.buffer_size)
        pos = int(rb._pos)
        if getattr(rb, "full", False):
            order = np.concatenate([np.arange(pos, size), np.arange(0, pos)])
        else:
            order = np.arange(pos)
        if order.size == 0:
            return
        data = {key: np.asarray(rb[key])[order] for key in rb.buffer.keys()}
        self.add(data, env_idxes)
