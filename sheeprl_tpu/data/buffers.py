"""Host-side replay buffers.

The data layer stays on the TPU-VM host as numpy (optionally memmapped to
disk), exactly like the reference keeps buffers host-side in torch-land
(SURVEY §1 L1); device placement happens only at sample time. Shapes are
``[time, n_envs, ...]`` throughout.

Behavioral parity targets (fresh implementation, same contracts):
- ``ReplayBuffer``            — sheeprl/data/buffers.py:20-360
- ``SequentialReplayBuffer``  — sheeprl/data/buffers.py:363-526
- ``EnvIndependentReplayBuffer`` — sheeprl/data/buffers.py:529-743
- ``EpisodeBuffer``           — sheeprl/data/buffers.py:746-1155
- np→device bridge            — sheeprl/data/buffers.py:1158-1180 (get_tensor)

The device bridge returns JAX arrays: ``sample_tensors`` accepts an optional
jax.sharding.Sharding so samples land pre-sharded across the mesh (no
single-chip gather), which is the TPU-native analog of `.to(device)`.
"""

from __future__ import annotations

import os
import shutil
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from sheeprl_tpu.data.memmap import _VALID_MODES, MemmapArray
from sheeprl_tpu.telemetry.tracer import current as _current_tracer

def get_array(
    value: "np.ndarray | MemmapArray",
    dtype: Optional[Any] = None,
    clone: bool = False,
    device: Optional[Any] = None,
):
    """np→device bridge (analog of reference get_tensor, buffers.py:1158-1180).

    ``device`` may be None (stay numpy), a jax.Device, or a Sharding; dtype is
    any jax/numpy dtype.
    """
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(dtype, copy=False)
    if device is None:
        return arr.copy() if clone else arr
    import jax

    return jax.device_put(arr, device)


def _validate_add_data(data: Dict[str, np.ndarray]) -> None:
    if not isinstance(data, dict):
        raise ValueError(f"'data' must be a dictionary containing Numpy arrays, got type '{type(data)}'")
    shape = None
    ref_key = None
    for k, v in data.items():
        if not isinstance(v, (np.ndarray, MemmapArray)):
            raise ValueError(f"'data' must contain Numpy arrays. Key '{k}' has type '{type(v)}'")
        if v.ndim < 2:
            raise RuntimeError(
                f"'data' must have at least 2 dimensions: [sequence_length, n_envs, ...]. Shape of '{k}' is {v.shape}"
            )
        if shape is None:
            shape, ref_key = v.shape[:2], k
        elif v.shape[:2] != shape:
            raise RuntimeError(
                "Every array in 'data' must be congruent in the first 2 dimensions: "
                f"found key '{ref_key}' with shape '{shape}' and '{k}' with shape '{v.shape[:2]}'"
            )



def _seeded_sampling_rng() -> np.random.Generator:
    """Sampling stream derived from the (seeded, rank-folded) global RNG.

    An unseeded ``default_rng()`` (OS entropy) made replay sampling the last
    nondeterministic draw in a seeded run — same-seed off-policy trainings
    diverged after the prefill. Deriving from the global RNG makes buffers
    deterministic under ``seed_everything``; reproducibility then tracks
    buffer CONSTRUCTION ORDER — call ``.seed(n)`` for a stream pinned
    independently of it. (dtype pinned: the legacy randint bound would
    overflow a C-long int32 on ILP32 platforms.)
    """
    return np.random.default_rng(int(np.random.randint(0, 2**31, dtype=np.int64)))

class ReplayBuffer:
    """Circular [buffer_size, n_envs, ...] dict-of-arrays buffer with uniform
    sampling and wraparound-safe next-observation sampling."""

    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: "str | os.PathLike | None" = None,
        memmap_mode: str = "r+",
        **kwargs,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = Path(memmap_dir) if memmap_dir is not None else None
        self._memmap_mode = memmap_mode
        if self._memmap:
            if memmap_mode not in _VALID_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_VALID_MODES}, got '{memmap_mode}'")
            if self._memmap_dir is None:
                raise ValueError(
                    "The buffer is set to be memory-mapped but 'memmap_dir' is None. Set it to a known directory."
                )
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buf: Dict[str, Any] = {}
        self._pos = 0
        self._full = False
        self._rng = _seeded_sampling_rng()

    # ----------------------------------------------------------- properties
    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> bool:
        return self._full

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> bool:
        return not self._buf

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # ----------------------------------------------------------------- add
    def _allocate(self, key: str, value: np.ndarray) -> None:
        shape = (self._buffer_size, self._n_envs, *value.shape[2:])
        if self._memmap:
            self._buf[key] = MemmapArray(
                filename=self._memmap_dir / f"{key}.memmap",
                dtype=value.dtype,
                shape=shape,
                mode=self._memmap_mode,
            )
        else:
            self._buf[key] = np.empty(shape, dtype=value.dtype)

    def add(self, data: "ReplayBuffer | Dict[str, np.ndarray]", validate_args: bool = False) -> None:
        """Write a [T, n_envs, ...] chunk at the circular head, overwriting the
        oldest data when full."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
        data_len = next(iter(data.values())).shape[0]
        if data_len > self._buffer_size:
            # Only the last buffer_size steps can survive; drop the rest.
            data = {k: v[-self._buffer_size :] for k, v in data.items()}
            data_len = self._buffer_size
        idxes = np.arange(self._pos, self._pos + data_len) % self._buffer_size
        # All keys must be declared by the first add: allocating a key later
        # would leave np.empty garbage at every previously-written position,
        # which sample() would then serve as real data. The reference fails
        # loudly here too (KeyError at buffers.py:216).
        has_keys = bool(self._buf)
        for k, v in data.items():
            if k not in self._buf:
                if has_keys:
                    raise KeyError(
                        f"Key '{k}' was not present in the first add(); all keys must be added from the start "
                        f"(existing keys: {sorted(self._buf)})"
                    )
                self._allocate(k, np.asarray(v))
            self._buf[k][idxes] = v
        if self._pos + data_len >= self._buffer_size:
            self._full = True
        self._pos = (self._pos + data_len) % self._buffer_size

    # -------------------------------------------------------------- sample
    def _valid_indices(self, sample_next_obs: bool) -> np.ndarray:
        """Uniform-sampleable time indices, excluding the transition that
        straddles the write head (its next-obs belongs to a different
        trajectory)."""
        if self._full:
            first_end = self._pos - 1 if sample_next_obs else self._pos
            second_end = self._buffer_size if first_end >= 0 else self._buffer_size + first_end
            return np.concatenate(
                [np.arange(0, max(first_end, 0)), np.arange(self._pos, second_end)]
            ).astype(np.intp)
        max_pos = self._pos - 1 if sample_next_obs else self._pos
        if max_pos <= 0:
            raise RuntimeError(
                "Cannot sample next observations with a single element in the buffer. Add at least two samples."
            )
        return np.arange(0, max_pos, dtype=np.intp)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs,
    ) -> Dict[str, np.ndarray]:
        """Uniform sample; returns [n_samples, batch_size, ...]."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer. Please add at least one sample calling 'add()'")
        valid = self._valid_indices(sample_next_obs)
        time_idxes = valid[self._rng.integers(0, len(valid), size=(batch_size * n_samples,), dtype=np.intp)]
        out = self._gather(time_idxes, sample_next_obs=sample_next_obs, clone=clone)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in out.items()}

    def _gather(self, time_idxes: np.ndarray, sample_next_obs: bool, clone: bool) -> Dict[str, np.ndarray]:
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        env_idxes = self._rng.integers(0, self._n_envs, size=(len(time_idxes),), dtype=np.intp)
        flat = time_idxes * self._n_envs + env_idxes
        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = np.asarray(v)
            flat_view = arr.reshape(-1, *arr.shape[2:])
            out[k] = flat_view[flat].copy() if clone else flat_view[flat]
            if sample_next_obs and k in self._obs_keys:
                nxt = ((time_idxes + 1) % self._buffer_size) * self._n_envs + env_idxes
                out[f"next_{k}"] = flat_view[nxt].copy() if clone else flat_view[nxt]
        return out

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs,
    ) -> Dict[str, Any]:
        """Sample and move to device (optionally pre-sharded across a mesh)."""
        n_samples = kwargs.pop("n_samples", 1)
        with _current_tracer().span("replay/sample", "replay", batch_size=int(batch_size)):
            samples = self.sample(
                batch_size, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs
            )
        return {k: get_array(v, dtype=dtype, device=device) for k, v in samples.items()}

    def to_tensor(self, dtype: Optional[Any] = None, clone: bool = False, device: Optional[Any] = None) -> Dict[str, Any]:
        return {k: get_array(v, dtype=dtype, clone=clone, device=device) for k, v in self._buf.items()}

    # ------------------------------------------------------------- mapping
    def __getitem__(self, key: str) -> np.ndarray:
        if not isinstance(key, str):
            raise TypeError("'key' must be a string")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        return self._buf.get(key)

    def __setitem__(self, key: str, value: "np.ndarray | MemmapArray") -> None:
        if not isinstance(value, (np.ndarray, MemmapArray)):
            raise ValueError(f"The value must be np.ndarray or MemmapArray, got {type(value)}")
        if value.shape[:2] != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"'value' must have shape [buffer_size, n_envs, ...]. Shape of 'value' is {value.shape}"
            )
        if self._memmap:
            filename = value.filename if isinstance(value, MemmapArray) else self._memmap_dir / f"{key}.memmap"
            # The displaced entry may own the very file the replacement maps;
            # revoke its ownership first or its __del__ unlinks the live file.
            old = self._buf.get(key)
            if isinstance(old, MemmapArray) and old.filename == Path(filename).absolute():
                old.has_ownership = False
            self._buf[key] = MemmapArray.from_array(value, filename=filename, mode=self._memmap_mode)
        else:
            self._buf[key] = np.array(value, copy=True)


class SequentialReplayBuffer(ReplayBuffer):
    """Samples contiguous [n_samples, sequence_length, batch_size, ...] windows
    ignoring episode boundaries, avoiding the invalid region around the write
    head (reference: buffers.py:439-456)."""

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs,
    ) -> Dict[str, np.ndarray]:
        batch_dim = batch_size * n_samples
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer. Please add at least one sample calling 'add()'")
        if not self._full and self._pos - sequence_length + 1 < 1:
            raise ValueError(f"Cannot sample a sequence of length {sequence_length}. Data added so far: {self._pos}")
        if self._full and sequence_length > self._buffer_size:
            raise ValueError(
                f"The sequence length ({sequence_length}) is greater than the buffer size ({self._buffer_size})"
            )

        if self._full:
            # Valid starts are those whose sequence does not cross the write
            # head: [0, pos - L] plus [pos, size) (shrunk when the first
            # interval is empty so the tail can't wrap into invalid data).
            first_end = self._pos - sequence_length + 1
            second_end = self._buffer_size if first_end >= 0 else self._buffer_size + first_end
            valid = np.concatenate([np.arange(0, max(first_end, 0)), np.arange(self._pos, second_end)]).astype(np.intp)
            starts = valid[self._rng.integers(0, len(valid), size=(batch_dim,), dtype=np.intp)]
        else:
            # With sample_next_obs the slot at _pos is read via time_idxes+1,
            # but it has never been written on a non-full buffer: shrink the
            # start range by one (improves on the reference, which emits
            # uninitialized memory here).
            max_start = self._pos - sequence_length + 1 - int(sample_next_obs)
            if max_start <= 0:
                raise RuntimeError(
                    f"Cannot sample a sequence of length {sequence_length} "
                    f"(sample_next_obs={sample_next_obs}) with only {self._pos} steps in the buffer"
                )
            starts = self._rng.integers(0, max_start, size=(batch_dim,), dtype=np.intp)

        offsets = np.arange(sequence_length, dtype=np.intp)[None, :]
        time_idxes = (starts[:, None] + offsets) % self._buffer_size  # [batch_dim, L]

        # One environment per sequence.
        env_idxes = self._rng.integers(0, self._n_envs, size=(batch_dim,), dtype=np.intp)
        flat = (time_idxes * self._n_envs + env_idxes[:, None]).ravel()

        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = np.asarray(v)
            flat_view = arr.reshape(-1, *arr.shape[2:])
            g = flat_view[flat].reshape(n_samples, batch_size, sequence_length, *arr.shape[2:])
            out[k] = np.swapaxes(g, 1, 2)  # → [n_samples, L, batch, ...]
            if clone:
                out[k] = out[k].copy()
            if sample_next_obs:
                # Reference parity: the sequential buffer emits next_{k} for
                # EVERY key, not just obs_keys (buffers.py:514-527).
                nxt = (((time_idxes + 1) % self._buffer_size) * self._n_envs + env_idxes[:, None]).ravel()
                gn = flat_view[nxt].reshape(n_samples, batch_size, sequence_length, *arr.shape[2:])
                out[f"next_{k}"] = np.swapaxes(gn, 1, 2)
                if clone:
                    out[f"next_{k}"] = out[f"next_{k}"].copy()
        return out


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment so sampled sequences never cross env
    boundaries; batch split multinomially across envs at sample time."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: "str | os.PathLike | None" = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        **kwargs,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap and memmap_dir is None:
            raise ValueError(
                "The buffer is set to be memory-mapped but 'memmap_dir' is None. Set it to a known directory."
            )
        self._buf: List[ReplayBuffer] = [
            buffer_cls(
                buffer_size=buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=Path(memmap_dir) / f"env_{i}" if memmap else None,
                memmap_mode=memmap_mode,
                **kwargs,
            )
            for i in range(n_envs)
        ]
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._rng = _seeded_sampling_rng()
        self._concat_along_axis = buffer_cls.batch_axis

    @property
    def buffer(self) -> Sequence[ReplayBuffer]:
        return tuple(self._buf)

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> Sequence[bool]:
        return tuple(b.full for b in self._buf)

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> Sequence[bool]:
        return tuple(b.empty for b in self._buf)

    @property
    def is_memmap(self) -> Sequence[bool]:
        return tuple(b.is_memmap for b in self._buf)

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)
        for i, b in enumerate(self._buf):
            b.seed(None if seed is None else seed + i + 1)

    def add(
        self,
        data: "ReplayBuffer | Dict[str, np.ndarray]",
        indices: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if indices is None:
            indices = tuple(range(self._n_envs))
        elif len(indices) != next(iter(data.values())).shape[1]:
            raise ValueError(
                f"The length of 'indices' ({len(indices)}) must be equal to the second dimension of the "
                f"arrays in 'data' ({next(iter(data.values())).shape[1]})"
            )
        for data_col, env_idx in enumerate(indices):
            env_data = {k: v[:, data_col : data_col + 1] for k, v in data.items()}
            self._buf[env_idx].add(env_data, validate_args=validate_args)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        per_env = np.bincount(self._rng.integers(0, self._n_envs, (batch_size,)))
        parts = [
            b.sample(batch_size=bs, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs)
            for b, bs in zip(self._buf, per_env)
            if bs > 0
        ]
        return {k: np.concatenate([p[k] for p in parts], axis=self._concat_along_axis) for k in parts[0]}

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs,
    ) -> Dict[str, Any]:
        with _current_tracer().span("replay/sample", "replay", batch_size=int(batch_size)):
            samples = self.sample(
                batch_size=batch_size, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs
            )
        return {k: get_array(v, dtype=dtype, device=device) for k, v in samples.items()}


class EpisodeBuffer:
    """Whole-episode storage (DreamerV2's episodic replay): tracks one open
    episode per env, saves an episode when its final done arrives, evicts the
    oldest episodes over capacity, and samples in-episode windows with
    optional ``prioritize_ends``."""

    batch_axis: int = 2

    def __init__(
        self,
        buffer_size: int,
        minimum_episode_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: "str | os.PathLike | None" = None,
        memmap_mode: str = "r+",
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if minimum_episode_length <= 0:
            raise ValueError(f"The sequence length must be greater than zero, got: {minimum_episode_length}")
        if buffer_size < minimum_episode_length:
            raise ValueError(
                f"The sequence length must be lower than the buffer size, got: bs = {buffer_size} "
                f"and sl = {minimum_episode_length}"
            )
        self._buffer_size = buffer_size
        self._minimum_episode_length = minimum_episode_length
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._prioritize_ends = prioritize_ends
        self._memmap = memmap
        self._memmap_dir = Path(memmap_dir) if memmap_dir is not None else None
        self._memmap_mode = memmap_mode
        if self._memmap:
            if self._memmap_dir is None:
                raise ValueError(
                    "The buffer is set to be memory-mapped but 'memmap_dir' is None. Set it to a known directory."
                )
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._open_episodes: List[List[Dict[str, np.ndarray]]] = [[] for _ in range(n_envs)]
        self._cum_lengths: List[int] = []
        self._buf: List[Dict[str, Any]] = []
        self._rng = _seeded_sampling_rng()

    # ----------------------------------------------------------- properties
    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @prioritize_ends.setter
    def prioritize_ends(self, value: bool) -> None:
        self._prioritize_ends = value

    @property
    def buffer(self) -> Sequence[Dict[str, np.ndarray]]:
        return self._buf

    @property
    def obs_keys(self) -> Sequence[str]:
        return self._obs_keys

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def minimum_episode_length(self) -> int:
        return self._minimum_episode_length

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def full(self) -> bool:
        return self._cum_lengths[-1] + self._minimum_episode_length > self._buffer_size if self._buf else False

    def __len__(self) -> int:
        return self._cum_lengths[-1] if self._buf else 0

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # ----------------------------------------------------------------- add
    def add(
        self,
        data: "ReplayBuffer | Dict[str, np.ndarray]",
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
            if "terminated" not in data or "truncated" not in data:
                raise RuntimeError(
                    f"The episode must contain the 'terminated' and the 'truncated' keys, got: {list(data.keys())}"
                )
            if env_idxes is not None and (np.asarray(env_idxes) >= self._n_envs).any():
                raise ValueError(
                    f"The indices of the environment must be integers in [0, {self._n_envs}), given {env_idxes}"
                )
        if env_idxes is None:
            env_idxes = range(self._n_envs)
        for data_col, env in enumerate(env_idxes):
            env_data = {k: v[:, data_col] for k, v in data.items()}
            done = np.logical_or(env_data["terminated"], env_data["truncated"]).flatten()
            ends = done.nonzero()[0].tolist()
            if not ends:
                self._open_episodes[env].append(env_data)
                continue
            start = 0
            for end in ends + [len(done) - 1]:
                chunk = {k: v[start : end + 1] for k, v in env_data.items()}
                if next(iter(chunk.values())).shape[0] > 0:
                    self._open_episodes[env].append(chunk)
                start = end + 1
                closed = self._open_episodes[env] and bool(
                    np.logical_or(
                        self._open_episodes[env][-1]["terminated"][-1],
                        self._open_episodes[env][-1]["truncated"][-1],
                    ).any()
                )
                if closed:
                    self._save_episode(self._open_episodes[env])
                    self._open_episodes[env] = []

    def _save_episode(self, chunks: Sequence[Dict[str, np.ndarray]]) -> None:
        if not chunks:
            raise RuntimeError("Invalid episode, an empty sequence is given. You must pass a non-empty sequence.")
        episode = {k: np.concatenate([c[k] for c in chunks], axis=0) for k in chunks[0]}
        ends = np.logical_or(episode["terminated"], episode["truncated"]).flatten()
        ep_len = ends.shape[0]
        if len(ends.nonzero()[0]) != 1 or not ends[-1]:
            raise RuntimeError(f"The episode must contain exactly one done, got: {len(ends.nonzero()[0])}")
        if ep_len < self._minimum_episode_length:
            raise RuntimeError(f"Episode too short (at least {self._minimum_episode_length} steps), got: {ep_len} steps")
        if ep_len > self._buffer_size:
            raise RuntimeError(f"Episode too long (at most {self._buffer_size} steps), got: {ep_len} steps")

        # Evict oldest episodes until the new one fits.
        if self.full or len(self) + ep_len > self._buffer_size:
            cum = np.array(self._cum_lengths)
            keep_from = int(((len(self) - cum + ep_len) <= self._buffer_size).argmax()) + 1
            for ep in self._buf[:keep_from]:
                if self._memmap:
                    dirname = os.path.dirname(next(iter(ep.values())).filename)
                    for v in ep.values():
                        v.has_ownership = False
                    ep.clear()
                    shutil.rmtree(dirname, ignore_errors=True)
            self._buf = self._buf[keep_from:]
            cum = cum[keep_from:] - cum[keep_from - 1]
            self._cum_lengths = cum.tolist()
        self._cum_lengths.append(len(self) + ep_len)

        if self._memmap:
            episode_dir = self._memmap_dir / f"episode_{uuid.uuid4()}"
            stored = {}
            for k, v in episode.items():
                stored[k] = MemmapArray(
                    filename=episode_dir / f"{k}.memmap", dtype=v.dtype, shape=v.shape, mode=self._memmap_mode
                )
                stored[k][:] = v
            self._buf.append(stored)
        else:
            self._buf.append(episode)

    # -------------------------------------------------------------- sample
    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        **kwargs,
    ) -> Dict[str, np.ndarray]:
        """Sample [n_samples, sequence_length, batch_size, ...] windows drawn
        within episodes."""
        if batch_size <= 0:
            raise ValueError(f"Batch size must be greater than 0, got: {batch_size}")
        if n_samples <= 0:
            raise ValueError(f"The number of samples must be greater than 0, got: {n_samples}")
        lengths = np.array(self._cum_lengths) - np.array([0] + self._cum_lengths[:-1])
        ok = lengths > sequence_length if sample_next_obs else lengths >= sequence_length
        valid_eps = [ep for ep, good in zip(self._buf, ok) if good]
        if not valid_eps:
            raise RuntimeError(
                "No valid episodes has been added to the buffer. Please add at least one episode of length greater "
                f"than or equal to {sequence_length} calling 'add()'"
            )
        offsets = np.arange(sequence_length, dtype=np.intp)[None, :]
        counts = np.bincount(self._rng.integers(0, len(valid_eps), (batch_size * n_samples,))).astype(np.intp)
        collected: Dict[str, List[np.ndarray]] = {k: [] for k in valid_eps[0]}
        if sample_next_obs:
            collected.update({f"next_{k}": [] for k in self._obs_keys})
        for i, n in enumerate(counts):
            if n == 0:
                continue
            ep = valid_eps[i]
            ep_len = np.logical_or(ep["terminated"], ep["truncated"]).shape[0]
            if sample_next_obs:
                ep_len -= 1
            upper = ep_len - sequence_length + 1
            if self._prioritize_ends:
                # Allow starts past the last full window; clamping them to the
                # final window oversamples episode endings.
                upper += sequence_length
            starts = np.minimum(
                self._rng.integers(0, upper, size=(n,)).reshape(-1, 1), ep_len - sequence_length
            ).astype(np.intp)
            indices = starts + offsets
            for k in ep:
                arr = np.asarray(ep[k])
                collected[k].append(arr[indices.ravel()].reshape(n, sequence_length, *arr.shape[1:]))
                if sample_next_obs and k in self._obs_keys:
                    collected[f"next_{k}"].append(arr[(indices + 1).ravel()].reshape(n, sequence_length, *arr.shape[1:]))
        out = {}
        for k, v in collected.items():
            if v:
                stacked = np.concatenate(v, axis=0).reshape(n_samples, batch_size, sequence_length, *v[0].shape[2:])
                out[k] = np.moveaxis(stacked, 2, 1)
                if clone:
                    out[k] = out[k].copy()
        return out

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs,
    ) -> Dict[str, Any]:
        with _current_tracer().span("replay/sample", "replay", batch_size=int(batch_size)):
            samples = self.sample(batch_size, sample_next_obs, n_samples, clone, sequence_length)
        return {k: get_array(v, dtype=dtype, device=device) for k, v in samples.items()}
