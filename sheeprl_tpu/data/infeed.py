"""Asynchronous host→device infeed for replay-sampled training batches.

The off-policy loops (SAC, all Dreamers, P2E) alternate between env stepping
(host-bound) and a train call whose batches must first be copied host→device.
Synchronously, that copy serializes with everything else: for a Dreamer
recipe the per-call batch is ~13 MB of uint8 pixels, tens of milliseconds of
host time that the chip spends idle — and over a remote link it is worse.

`AsyncInfeed` overlaps the copy with env stepping (SURVEY §7.1 step 3,
"sample on host threads → double-buffered device_put"):

- `stage(host_batches)` is called right after a train call is dispatched,
  with batches ALREADY SAMPLED on the caller's thread — sampling stays on
  the main thread, between buffer writes, so the replay buffer needs no
  locking. A worker thread then runs the host→device transfers while the
  caller returns to stepping envs (numpy slicing and `jax.device_put`
  release the GIL).
- `take()` at the next train call returns the staged device batches if the
  expected shape matches, or `None` (caller falls back to the synchronous
  path — e.g. the Ratio controller asked for a different gradient-step
  count, or nothing was staged).

The worker only ever touches host arrays handed to it by value; it never
reads the replay buffer, so there is no concurrent-mutation hazard.
"""

from __future__ import annotations

import concurrent.futures
from typing import Any, Callable, List, Optional

from sheeprl_tpu.telemetry.tracer import current as _current_tracer


class AsyncInfeed:
    """Double-buffered device staging of pre-sampled host batches."""

    def __init__(self, put_fn: Callable[[Any], Any]) -> None:
        """``put_fn(host_batch) -> device_batch`` runs on the worker thread."""
        self._put_fn = put_fn
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sheeprl-infeed"
        )
        self._future: Optional[concurrent.futures.Future] = None
        self._staged_count: Optional[int] = None
        self.hits = 0
        self.misses = 0

    def stage(self, host_batches: List[Any]) -> None:
        """Hand sampled host batches to the worker for device transfer.

        Any previously staged result that was never taken is dropped (its
        transfers were already enqueued; the arrays are simply released).
        """
        batches = list(host_batches)

        def work():
            # Worker thread: the tracer is thread-safe, and the span makes the
            # overlapped H2D staging visible on its own trace track.
            with _current_tracer().span("transfer/h2d_stage", "transfer", batches=len(batches)):
                return [self._put_fn(b) for b in batches]

        self._staged_count = len(batches)
        self._future = self._executor.submit(work)

    def take(self, expected_count: int) -> Optional[List[Any]]:
        """Return `expected_count` staged device batches, or None.

        A larger stage serves its first `expected_count` batches (the Ratio
        controller's step count can drift by one between calls); a smaller
        stage is a miss and the caller falls back to synchronous sampling.
        """
        future, count = self._future, self._staged_count
        self._future = None
        self._staged_count = None
        if future is None or count < expected_count:
            if future is not None:
                future.cancel()
            self.misses += 1
            return None
        self.hits += 1
        return future.result()[:expected_count]

    def close(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)


class ReplayInfeed:
    """The sample→stage→take protocol shared by every sequence-replay loop.

    Bundles an :class:`AsyncInfeed` with the Dreamer-family batch recipe:
    host batches come from ``rb.sample_tensors`` (always on the caller's
    thread — no concurrent buffer access), CNN-keyed entries stay in their
    storage dtype (uint8 pixels; normalized inside jit) and everything else
    is converted to float32 on the way to the device.
    """

    def __init__(self, rb, batch_size: int, sequence_length: int, cnn_keys, *, enabled: bool = True) -> None:
        import jax.numpy as jnp
        import numpy as np

        self._rb = rb
        self._batch_size = int(batch_size)
        self._sequence_length = int(sequence_length)
        cnn_key_set = set(cnn_keys)

        def device_batch(host_batch):
            # Count the host->device traffic before conversion (the host
            # array's nbytes is what actually crosses the PCIe/ICI link);
            # the tracer is thread-safe, so this is fine on the worker.
            trc = _current_tracer()
            if trc.enabled:
                nbytes = sum(int(getattr(v, "nbytes", 0)) for v in host_batch.values())
                trc.count("host_to_device_calls", 1)
                trc.count("host_to_device_bytes", nbytes)
            return {
                k: jnp.asarray(v, jnp.float32) if k not in cnn_key_set else jnp.asarray(v)
                for k, v in host_batch.items()
            }

        self._device_batch = device_batch
        self._np = np
        self._infeed = AsyncInfeed(device_batch) if enabled else None

    def _sample_host(self, n: int) -> List[Any]:
        data = self._rb.sample_tensors(
            self._batch_size, sequence_length=self._sequence_length, n_samples=n
        )
        np = self._np
        return [{k: np.asarray(v[i]) for k, v in data.items()} for i in range(n)]

    def take_or_sample(self, n: int) -> List[Any]:
        """Staged device batches if available, else sample+copy synchronously."""
        batches = self._infeed.take(n) if self._infeed is not None else None
        if batches is None:
            with _current_tracer().span("transfer/h2d_sync", "transfer", batches=n):
                batches = [self._device_batch(b) for b in self._sample_host(n)]
        return batches

    def stage(self, n: int) -> None:
        """Sample the next call's batches now (caller's thread) and hand the
        device copies to the worker to overlap the env-step phase."""
        if self._infeed is not None:
            self._infeed.stage(self._sample_host(n))

    def close(self) -> None:
        if self._infeed is not None:
            self._infeed.close()
