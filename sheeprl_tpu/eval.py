"""`python -m sheeprl_tpu.eval` → evaluation CLI
(reference console script `sheeprl-eval`)."""

from sheeprl_tpu.cli import evaluation

if __name__ == "__main__":
    evaluation()
