"""Traced math ops shared across algorithms (pure JAX, jit-safe).

Formula parity with the reference's tensor utilities, restructured for XLA:
the reference's reverse Python loops (GAE at sheeprl/utils/utils.py:63-100,
λ-values at sheeprl/algos/dreamer_v3/utils.py:66-77) become `lax.scan` over
the time axis — traced once, fused by XLA, no per-step dispatch. Everything
here is shape-polymorphic over leading batch dims and safe under `jit`/`pjit`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------- symlog
def symlog(x: jax.Array) -> jax.Array:
    """sign(x) * log(1 + |x|) (reference: sheeprl/utils/utils.py:148-149)."""
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    """sign(x) * (exp(|x|) - 1) (reference: sheeprl/utils/utils.py:152-153)."""
    return jnp.sign(x) * jnp.expm1(jnp.abs(x))


# --------------------------------------------------------------- two-hot
def two_hot_encoder(x: jax.Array, support_range: int = 300, num_buckets: Optional[int] = None) -> jax.Array:
    """Encode scalars (..., 1) as two-hot vectors (..., num_buckets) over a
    symmetric integer support (reference: sheeprl/utils/utils.py:156-190;
    DreamerV3 paper eq. 9).
    """
    if x.ndim == 0:
        x = x[None]
    if num_buckets is None:
        num_buckets = support_range * 2 + 1
    if num_buckets % 2 == 0:
        raise ValueError("support_size must be odd")
    x = jnp.clip(x, -support_range, support_range)
    buckets = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    bucket_size = buckets[1] - buckets[0] if num_buckets > 1 else jnp.asarray(1.0, x.dtype)

    # torch.bucketize(right=False) == searchsorted(side='left')
    right = jnp.searchsorted(buckets, x, side="left")
    left = jnp.clip(right - 1, 0, None)

    left_value = jnp.abs(buckets[right] - x) / bucket_size
    right_value = 1.0 - left_value
    lhot = jax.nn.one_hot(left[..., 0], num_buckets, dtype=x.dtype) * left_value
    rhot = jax.nn.one_hot(right[..., 0], num_buckets, dtype=x.dtype) * right_value
    return lhot + rhot


def two_hot_decoder(x: jax.Array, support_range: int) -> jax.Array:
    """Decode two-hot vectors (..., num_buckets) back to scalars (..., 1)
    (reference: sheeprl/utils/utils.py:193-205)."""
    num_buckets = x.shape[-1]
    if num_buckets % 2 == 0:
        raise ValueError("support_size must be odd")
    support = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    return jnp.sum(x * support, axis=-1, keepdims=True)


# ------------------------------------------------------------------- gae
def _gae_preamble(rewards, values, dones, next_value, gamma):
    # fp32 island: return/advantage accumulation is never done in bf16
    # (parity with the reference keeping these ops in fp32; SURVEY §7.2).
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    next_value = next_value.astype(jnp.float32)
    not_dones = (1.0 - dones).astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)
    deltas = rewards + gamma * not_dones * next_values - values
    return values, deltas, not_dones


def _lambda_preamble(rewards, values, continues, lmbda):
    # fp32 island: TD(λ) accumulation stays out of bf16 whatever the policy.
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    continues = continues.astype(jnp.float32)
    interm = rewards + continues * values * (1 - lmbda)
    return values, interm, continues


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation over [T, ...] arrays.

    Semantics match the reference loop (sheeprl/utils/utils.py:63-100):
    delta[t] = r[t] + gamma * not_done[t] * V[t+1] - V[t] with V[T] =
    next_value, and adv[t] = delta[t] + gamma * lambda * not_done[t] *
    adv[t+1] — here as one reverse `lax.scan`. Returns (returns, advantages).
    """
    values, deltas, not_dones = _gae_preamble(rewards, values, dones, next_value, gamma)

    def step(carry, x):
        delta, nd = x
        carry = delta + gamma * gae_lambda * nd * carry
        return carry, carry

    _, adv = jax.lax.scan(step, jnp.zeros_like(deltas[0]), (deltas, not_dones), reverse=True)
    return adv + values, adv


# ---------------------------------------------------------- lambda values
def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(λ) targets for imagined trajectories, [T, ...] → [T, ...].

    Reference reverse loop: sheeprl/algos/dreamer_v3/utils.py:66-77 —
    L[t] = r[t] + c[t] * ((1 - λ) * V[t] + λ * L[t+1]), seeded L[T] = V[T-1].
    """
    values, interm, continues = _lambda_preamble(rewards, values, continues, lmbda)

    def step(nxt, x):
        i, c = x
        v = i + c * lmbda * nxt
        return v, v

    _, out = jax.lax.scan(step, values[-1], (interm, continues), reverse=True)
    return out


# ------------------------------------------- parallel-time formulations
def _affine_suffix_scan(a: jax.Array, b: jax.Array, seed: jax.Array) -> jax.Array:
    """Solve y[t] = b[t] + a[t] * y[t+1] (y[T] = seed) for all t in
    O(log T) depth via `jax.lax.associative_scan` — the blockwise/parallel
    alternative to the O(T) reverse `lax.scan` (SURVEY §5.7's long-sequence
    hook). The pair (a, b) composes as an affine map y -> a*y + b.
    """
    # Fold the seed into the last element: y[T-1] = b[T-1] + a[T-1]*seed.
    b = b.at[-1].add(a[-1] * seed)

    def combine(later, earlier):
        # earlier maps y_{t+k} -> y_t given later maps y_{t+k+m} -> y_{t+k}
        a_l, b_l = later
        a_e, b_e = earlier
        return a_e * a_l, a_e * b_l + b_e

    _, y = jax.lax.associative_scan(combine, (a, b), reverse=True)
    return y


def gae_associative(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """`gae` with the time recurrence as an associative scan (O(log T)
    depth) — mathematically equivalent (matches to fp32 tolerance; the
    reassociated reduction rounds differently); preferable for very long
    rollouts."""
    values, deltas, not_dones = _gae_preamble(rewards, values, dones, next_value, gamma)
    adv = _affine_suffix_scan(gamma * gae_lambda * not_dones, deltas, jnp.zeros_like(deltas[0]))
    return adv + values, adv


def compute_lambda_values_associative(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """`compute_lambda_values` with the recurrence as an associative scan
    (mathematically equivalent; matches to fp32 tolerance)."""
    values, interm, continues = _lambda_preamble(rewards, values, continues, lmbda)
    return _affine_suffix_scan(continues * lmbda, interm, values[-1])


# -------------------------------------------------------------- normalize
def normalize_tensor(x: jax.Array, eps: float = 1e-8, mask: Optional[jax.Array] = None) -> jax.Array:
    """(x - mean) / (std + eps), optionally over a boolean mask
    (reference: sheeprl/utils/utils.py:121-130). With a mask, statistics are
    computed over selected elements only; masked-out entries are returned
    normalized with those statistics (shape is preserved — under jit we cannot
    return a ragged selection like the reference does).
    """
    if mask is None:
        std = jnp.std(x, ddof=1) if x.size > 1 else jnp.asarray(0.0, x.dtype)
        return (x - jnp.mean(x)) / (std + eps)
    n = jnp.maximum(mask.sum(), 1)
    mean = jnp.where(mask, x, 0).sum() / n
    var = (jnp.where(mask, x - mean, 0) ** 2).sum() / jnp.maximum(n - 1, 1)
    return (x - mean) / (jnp.sqrt(var) + eps)


# ------------------------------------------------------------ safe atanh
def safetanh(x: jax.Array, eps: float) -> jax.Array:
    """tanh clamped away from ±1 (reference: sheeprl/utils/utils.py:304-308)."""
    lim = 1.0 - eps
    return jnp.clip(jnp.tanh(x), -lim, lim)


def safeatanh(y: jax.Array, eps: float) -> jax.Array:
    """atanh of input clamped away from ±1 (reference: utils.py:311-313)."""
    lim = 1.0 - eps
    return jnp.arctanh(jnp.clip(y, -lim, lim))


# ---------------------------------------------------------------- moments
def init_moments() -> dict:
    """Initial state for the EMA return-range tracker (reference `Moments`
    buffers, sheeprl/algos/dreamer_v3/utils.py:40-56)."""
    return {"low": jnp.zeros((), jnp.float32), "high": jnp.zeros((), jnp.float32)}


def update_moments(
    state: dict,
    x: jax.Array,
    decay: float = 0.99,
    max_: float = 1e8,
    percentile_low: float = 0.05,
    percentile_high: float = 0.95,
) -> Tuple[dict, Tuple[jax.Array, jax.Array]]:
    """EMA 5/95-percentile return normalizer (reference: Moments.forward,
    sheeprl/algos/dreamer_v3/utils.py:57-63). Returns (new_state, (low,
    invscale)).

    The reference all_gathers `x` across ranks before the quantile; here the
    caller runs this inside a pjit-sharded step, where `jnp.quantile` over a
    batch-sharded array *is* the global quantile — XLA inserts the gather on
    ICI automatically.
    """
    x = jax.lax.stop_gradient(x.astype(jnp.float32))
    low = jnp.quantile(x, percentile_low)
    high = jnp.quantile(x, percentile_high)
    new_low = decay * state["low"] + (1 - decay) * low
    new_high = decay * state["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / max_, new_high - new_low)
    return {"low": new_low, "high": new_high}, (new_low, invscale)
