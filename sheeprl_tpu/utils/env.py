"""Environment factory: the dict-obs normalization pipeline.

Parity with the reference `make_env` (sheeprl/utils/env.py:26-231): given the
composed config it returns a thunk building one fully-wrapped env — wrapper
instantiation, action repeat, velocity masking, dict-ification of the obs
space, resize/grayscale via cv2, channel handling, frame stack, actions/
reward-as-observation, time limit, episode statistics, video capture.

Deliberate TPU-layout divergence: pixels stay **channel-last (H, W, C)**
through the whole pipeline (the reference transposes to CHW for torch at
env.py:194). Built on gymnasium >= 1.0 (TransformObservation takes the new
observation_space argument; AddRenderObservation replaces
PixelObservationWrapper; RecordVideo replaces RecordVideoV0).
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Callable, Dict, Optional

import cv2
import gymnasium as gym
import numpy as np

from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    GrayscaleRenderWrapper,
    MaskVelocityWrapper,
    RestartOnException,
    RewardAsObservationWrapper,
)


def _dictify_obs(env: gym.Env, key: str) -> gym.Env:
    """Wrap a bare-Box observation into a one-key Dict obs space."""
    return gym.wrappers.TransformObservation(
        env,
        lambda obs: {key: obs},
        gym.spaces.Dict({key: env.observation_space}),
    )


def make_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    """Return a thunk that builds one wrapped environment (the unit the
    vector-env constructors consume)."""

    def thunk() -> gym.Env:
        instantiate_kwargs = {}
        if "seed" in cfg.env.wrapper:
            instantiate_kwargs["seed"] = seed
        if "rank" in cfg.env.wrapper:
            instantiate_kwargs["rank"] = rank + vector_env_idx
        env = instantiate(cfg.env.wrapper, **instantiate_kwargs)

        if isinstance(env.action_space, gym.spaces.Box):
            low, high = env.action_space.low, env.action_space.high
            if (
                np.all(np.isfinite(low))
                and np.all(np.isfinite(high))
                and np.all(high > low)  # degenerate dims would rescale to NaN
                and (np.any(low != -1.0) or np.any(high != 1.0))
            ):
                # Present every continuous env as [-1, 1] (divergence from
                # the reference, which only ever runs continuous control on
                # DMC where bounds are natively [-1, 1]): tanh-squashed
                # policies (the Dreamer actors) otherwise silently command
                # a fraction of the env's torque range — Pendulum's [-2, 2]
                # made swing-up unlearnable. SAC is unaffected: its
                # scale/bias are computed from the (rescaled) space.
                env = gym.wrappers.RescaleAction(env, -1.0, 1.0)

        is_atari = "AtariPreprocessing" in str(cfg.env.wrapper.get("_target_", ""))
        if cfg.env.action_repeat > 1 and not is_atari:
            # Atari frame skip lives inside AtariPreprocessing already.
            env = ActionRepeat(env, cfg.env.action_repeat)

        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        if not (
            isinstance(cfg.algo.mlp_keys.encoder, list)
            and isinstance(cfg.algo.cnn_keys.encoder, list)
            and len(cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder) > 0
        ):
            raise ValueError(
                "`algo.cnn_keys.encoder` and `algo.mlp_keys.encoder` must be lists of strings, got: "
                f"cnn encoder keys `{cfg.algo.cnn_keys.encoder}` of type `{type(cfg.algo.cnn_keys.encoder)}` "
                f"and mlp encoder keys `{cfg.algo.mlp_keys.encoder}` of type `{type(cfg.algo.mlp_keys.encoder)}`. "
                "Both must be non-empty lists."
            )

        # ------------------------------------------------- dict-ify the obs
        encoder_cnn_keys_length = len(cfg.algo.cnn_keys.encoder)
        encoder_mlp_keys_length = len(cfg.algo.mlp_keys.encoder)
        if isinstance(env.observation_space, gym.spaces.Box) and len(env.observation_space.shape) < 2:
            # Vector-only observation
            if encoder_cnn_keys_length > 0:
                if encoder_cnn_keys_length > 1:
                    warnings.warn(
                        "Multiple cnn keys have been specified and only one pixel observation "
                        f"is allowed in {cfg.env.id}, only the first one is kept: {cfg.algo.cnn_keys.encoder[0]}"
                    )
                # Render-as-pixels (reference used PixelObservationWrapper)
                env = gym.wrappers.AddRenderObservation(
                    env,
                    render_only=encoder_mlp_keys_length == 0,
                    render_key=cfg.algo.cnn_keys.encoder[0],
                    obs_key=cfg.algo.mlp_keys.encoder[0] if encoder_mlp_keys_length > 0 else "state",
                )
                if encoder_mlp_keys_length == 0:
                    # render_only leaves a bare pixel Box (no dict): wrap it
                    # under the cnn key like the pixel-only branch below.
                    env = _dictify_obs(env, cfg.algo.cnn_keys.encoder[0])
            else:
                if encoder_mlp_keys_length > 1:
                    warnings.warn(
                        "Multiple mlp keys have been specified and only one vector observation "
                        f"is allowed in {cfg.env.id}, only the first one is kept: {cfg.algo.mlp_keys.encoder[0]}"
                    )
                env = _dictify_obs(env, cfg.algo.mlp_keys.encoder[0])
        elif isinstance(env.observation_space, gym.spaces.Box) and 2 <= len(env.observation_space.shape) <= 3:
            # Pixel-only observation
            if encoder_cnn_keys_length > 1:
                warnings.warn(
                    "Multiple cnn keys have been specified and only one pixel observation "
                    f"is allowed in {cfg.env.id}, only the first one is kept: {cfg.algo.cnn_keys.encoder[0]}"
                )
            elif encoder_cnn_keys_length == 0:
                raise ValueError(
                    "You have selected a pixel observation but no cnn key has been specified. "
                    "Please set at least one cnn key in the config file: `algo.cnn_keys.encoder=[your_cnn_key]`"
                )
            env = _dictify_obs(env, cfg.algo.cnn_keys.encoder[0])

        requested = set(cfg.algo.mlp_keys.encoder + cfg.algo.cnn_keys.encoder)
        if len(requested.intersection(set(env.observation_space.keys()))) == 0:
            raise ValueError(
                f"The user specified keys `{sorted(requested)}` are not a subset of the "
                f"environment `{sorted(env.observation_space.keys())}` observation keys. "
                "Please check your config file."
            )

        env_cnn_keys = set(
            k for k in env.observation_space.spaces.keys() if len(env.observation_space[k].shape) in {2, 3}
        )
        cnn_keys = env_cnn_keys.intersection(set(cfg.algo.cnn_keys.encoder))

        # --------------------------------------- pixel pipeline (stay HWC)
        screen = cfg.env.screen_size

        def transform_obs(obs: Dict[str, Any]) -> Dict[str, Any]:
            for k in cnn_keys:
                current_obs = obs[k]
                shape = current_obs.shape
                is_3d = len(shape) == 3
                is_grayscale = not is_3d or shape[0] == 1 or shape[-1] == 1
                channel_first = is_3d and shape[0] in (1, 3) and shape[-1] not in (1, 3)

                # to 3-D, channel-last (cv2-native)
                if not is_3d:
                    current_obs = np.expand_dims(current_obs, axis=-1)
                elif channel_first:
                    current_obs = np.transpose(current_obs, (1, 2, 0))

                if current_obs.shape[:-1] != (screen, screen):
                    current_obs = cv2.resize(current_obs, (screen, screen), interpolation=cv2.INTER_AREA)

                if cfg.env.grayscale and not is_grayscale:
                    current_obs = cv2.cvtColor(current_obs, cv2.COLOR_RGB2GRAY)

                # cv2 drops the trailing single channel; restore to 3-D HWC
                if len(current_obs.shape) == 2:
                    current_obs = np.expand_dims(current_obs, axis=-1)
                    if not cfg.env.grayscale:
                        current_obs = np.repeat(current_obs, 3, axis=-1)

                obs[k] = current_obs
            return obs

        new_spaces = dict(env.observation_space.spaces)
        for k in cnn_keys:
            new_spaces[k] = gym.spaces.Box(0, 255, (screen, screen, 1 if cfg.env.grayscale else 3), np.uint8)
        env = gym.wrappers.TransformObservation(env, transform_obs, gym.spaces.Dict(new_spaces))

        if cnn_keys is not None and len(cnn_keys) > 0 and cfg.env.frame_stack > 1:
            if cfg.env.frame_stack_dilation <= 0:
                raise ValueError(
                    f"The frame stack dilation argument must be greater than zero, got: {cfg.env.frame_stack_dilation}"
                )
            env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        if cfg.env.actions_as_observation.num_stack > 0:
            env = ActionsAsObservationWrapper(env, **cfg.env.actions_as_observation)

        if cfg.env.reward_as_observation:
            env = RewardAsObservationWrapper(env)

        env.action_space.seed(seed)
        env.observation_space.seed(seed)
        if cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, max_episode_steps=cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if cfg.env.capture_video and rank == 0 and vector_env_idx == 0 and run_name is not None:
            if cfg.env.grayscale:
                env = GrayscaleRenderWrapper(env)
            env = gym.wrappers.RecordVideo(
                env,
                os.path.join(run_name, prefix + "_videos" if prefix else "videos"),
                disable_logger=True,
            )
        return env

    return thunk


def get_dummy_env(id: str, **kwargs: Any) -> gym.Env:
    """Instantiate a deterministic test env by id substring
    (reference: sheeprl/utils/env.py:234-249)."""
    if "continuous" in id:
        from sheeprl_tpu.envs.dummy import ContinuousDummyEnv

        return ContinuousDummyEnv(**kwargs)
    elif "multidiscrete" in id:
        from sheeprl_tpu.envs.dummy import MultiDiscreteDummyEnv

        return MultiDiscreteDummyEnv(**kwargs)
    elif "discrete" in id:
        from sheeprl_tpu.envs.dummy import DiscreteDummyEnv

        return DiscreteDummyEnv(**kwargs)
    raise ValueError(f"Unrecognized dummy environment: {id}")


def seed_vector_spaces(envs: gym.vector.VectorEnv, seed: int) -> None:
    """Seed the VECTOR env's batched action/observation spaces.

    make_env seeds each sub-env's own spaces, but gym.vector builds
    separate *batched* Space objects whose RNG is seeded from OS entropy —
    so `envs.action_space.sample()` (the prefill path of every off-policy
    algorithm) was the one nondeterministic draw left in a seeded run,
    making borderline learning validations flap run to run.

    Every algorithm constructs its training envs through
    :func:`make_vector_env`, which calls this; only bespoke vector envs
    built elsewhere need to call it directly."""
    envs.action_space.seed(seed)
    envs.observation_space.seed(seed)


def make_vector_env(
    cfg: Dict[str, Any],
    rank: int,
    log_dir: Optional[str] = None,
    restart_on_exception: bool = False,
) -> gym.vector.VectorEnv:
    """The canonical training vector env — the ONE construction every
    algorithm main uses (reference pattern: e.g. sheeprl/algos/ppo/ppo.py:
    137-150): `cfg.env.num_envs` wrapped envs with per-env seeds
    `cfg.seed + rank*num_envs + i`, video capture from global-rank-0's env 0
    only, same-step autoreset, and the batched action/observation spaces
    seeded (the off-policy prefill path draws from them).
    ``restart_on_exception`` wraps each env in RestartOnException — the
    long-horizon Dreamer runs' fault tolerance against crashy simulators."""
    base = rank * cfg.env.num_envs
    thunks = [
        make_env(
            cfg,
            cfg.seed + base + i,
            base,
            log_dir if rank == 0 else None,
            "train",
            vector_env_idx=i,
        )
        for i in range(cfg.env.num_envs)
    ]
    if restart_on_exception:
        thunks = [partial(RestartOnException, t) for t in thunks]
    res_cfg = cfg.get("resilience") or {}
    chaos_cfg = res_cfg.get("chaos") or {}
    if chaos_cfg.get("enabled", False):
        # Fault injection (core/chaos.py): env_step_raise injectors wrap the
        # targeted env thunk; a process-global fired registry keeps a
        # supervisor-rebuilt env from replaying the same configured fault.
        from sheeprl_tpu.core.chaos import wrap_env_thunks

        thunks = wrap_env_thunks(thunks, chaos_cfg.get("injectors") or [], base)
    tele_cfg = cfg.get("telemetry") or {}
    flight_cfg = (tele_cfg.get("flight") or {}) if hasattr(tele_cfg, "get") else {}
    if bool(flight_cfg.get("enabled", True)):
        # Distributed tracing (telemetry/flight.py): the thunk runs INSIDE
        # the worker process (async mode), where it adopts the env-var trace
        # carrier published by Telemetry.open and spills step-window spans —
        # the cross-process half of every flight dump. Because supervisor
        # restarts rebuild slices from these same thunks, restarted worker
        # generations rejoin the trace automatically.
        from sheeprl_tpu.telemetry.flight import traced_env_thunk

        thunks = [traced_env_thunk(t, base + i) for i, t in enumerate(thunks)]
    cls = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    slices = int(cfg.env.get("pipeline_slices", 1) or 1)
    sup_cfg = res_cfg.get("supervisor") or {}
    supervise = bool(sup_cfg.get("enabled", False))

    def make_slice(s0: int, s1: int) -> gym.vector.VectorEnv:
        return cls(thunks[s0:s1], autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)

    if supervise:
        # Supervised env workers (core/resilience.py): the vector env — or
        # each pipeline slice — becomes a restartable EnvSupervisor slot.
        # EnvSupervisor subclasses EnvSliceGroup, so the single-slot case is
        # still a drop-in vector env and the sliced case is still what
        # InteractionPipeline.interact expects.
        from sheeprl_tpu.core.interact import split_ranges
        from sheeprl_tpu.core.resilience import EnvSupervisor

        ranges = split_ranges(cfg.env.num_envs, max(1, slices))
        envs: gym.vector.VectorEnv = EnvSupervisor(
            [make_slice(s0, s1) for s0, s1 in ranges],
            [partial(make_slice, s0, s1) for s0, s1 in ranges],
            seed=cfg.seed + base,
            max_restarts=int(sup_cfg.get("max_restarts", 3)),
            backoff_base_s=float(sup_cfg.get("backoff_base_s", 0.05)),
            backoff_max_s=float(sup_cfg.get("backoff_max_s", 5.0)),
            backoff_jitter=float(sup_cfg.get("backoff_jitter", 0.25)),
        )
    elif slices <= 1:
        envs = make_slice(0, cfg.env.num_envs)
    else:
        # env.pipeline_slices > 1: one sub vector env per contiguous column
        # range, presented as one num_envs-wide env (core/interact.py). Env
        # order — and therefore per-env seeds, video capture on global env 0,
        # and sub-env RNG streams — is preserved.
        from sheeprl_tpu.core.interact import EnvSliceGroup, split_ranges

        sub_envs = [
            make_slice(s0, s1) for s0, s1 in split_ranges(cfg.env.num_envs, slices)
        ]
        envs = EnvSliceGroup(sub_envs)
    seed_vector_spaces(envs, cfg.seed + base)
    return envs
