"""Distributions as lightweight, jit-safe value classes (pure JAX).

Formula parity with the reference distribution library
(sheeprl/utils/distribution.py:25-416), without torch.distributions or any
external dependency: each class is a thin container of arrays built *inside*
traced functions, so construction is free under jit and all math fuses into
the surrounding graph. Sampling takes an explicit PRNG key (JAX style); in
JAX every sample through reparameterized math is an "rsample", and the
straight-through estimator is expressed with `stop_gradient`.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import erf, erfinv

from sheeprl_tpu.utils.ops import symexp, symlog

CONST_SQRT_2 = math.sqrt(2)
CONST_INV_SQRT_2PI = 1 / math.sqrt(2 * math.pi)
CONST_INV_SQRT_2 = 1 / math.sqrt(2)
CONST_LOG_INV_SQRT_2PI = math.log(CONST_INV_SQRT_2PI)
CONST_LOG_SQRT_2PI_E = 0.5 * math.log(2 * math.pi * math.e)


# ------------------------------------------------------------------ normal
class Normal:
    """Diagonal normal. log_prob/entropy per element; wrap in Independent to
    sum event dims."""

    def __init__(self, loc: jax.Array, scale: jax.Array):
        self.loc = loc
        self.scale = scale

    @property
    def mean(self) -> jax.Array:
        return self.loc

    @property
    def mode(self) -> jax.Array:
        return self.loc

    @property
    def stddev(self) -> jax.Array:
        return self.scale

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(key, shape, dtype=self.loc.dtype)
        return self.loc + self.scale * eps

    rsample = sample

    def log_prob(self, value: jax.Array) -> jax.Array:
        var = self.scale**2
        return -((value - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)

    def entropy(self) -> jax.Array:
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)


class Independent:
    """Reinterpret the last `reinterpreted_batch_ndims` batch dims as event
    dims: log_prob/entropy sum over them (torch.distributions.Independent)."""

    def __init__(self, base, reinterpreted_batch_ndims: int = 1):
        self.base = base
        self.ndims = reinterpreted_batch_ndims

    def _reduce(self, x: jax.Array) -> jax.Array:
        return x.sum(axis=tuple(range(-self.ndims, 0))) if self.ndims else x

    @property
    def mean(self) -> jax.Array:
        return self.base.mean

    @property
    def mode(self) -> jax.Array:
        return self.base.mode

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return self.base.sample(key, sample_shape)

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return self.base.rsample(key, sample_shape)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return self._reduce(self.base.log_prob(value))

    def entropy(self) -> jax.Array:
        return self._reduce(self.base.entropy())


# ------------------------------------------------------- truncated normal
class TruncatedStandardNormal:
    """Truncated standard normal on [a, b]
    (reference: sheeprl/utils/distribution.py:25-113, from torch_truncnorm)."""

    def __init__(self, a: jax.Array, b: jax.Array):
        self.a, self.b = jnp.broadcast_arrays(jnp.asarray(a), jnp.asarray(b))
        eps = jnp.finfo(self.a.dtype).eps
        self._dtype_min_gt_0 = eps
        self._dtype_max_lt_1 = 1 - eps
        self._little_phi_a = self._little_phi(self.a)
        self._little_phi_b = self._little_phi(self.b)
        self._big_phi_a = self._big_phi(self.a)
        self._big_phi_b = self._big_phi(self.b)
        self._Z = jnp.clip(self._big_phi_b - self._big_phi_a, eps, None)
        self._log_Z = jnp.log(self._Z)
        lpc_a = jnp.nan_to_num(self.a, nan=math.nan)
        lpc_b = jnp.nan_to_num(self.b, nan=math.nan)
        self._lpbb_m_lpaa_d_Z = (self._little_phi_b * lpc_b - self._little_phi_a * lpc_a) / self._Z
        self._mean = -(self._little_phi_b - self._little_phi_a) / self._Z
        self._variance = (
            1 - self._lpbb_m_lpaa_d_Z - ((self._little_phi_b - self._little_phi_a) / self._Z) ** 2
        )
        self._entropy = CONST_LOG_SQRT_2PI_E + self._log_Z - 0.5 * self._lpbb_m_lpaa_d_Z

    @property
    def mean(self) -> jax.Array:
        return self._mean

    @property
    def variance(self) -> jax.Array:
        return self._variance

    @staticmethod
    def _little_phi(x: jax.Array) -> jax.Array:
        return jnp.exp(-(x**2) * 0.5) * CONST_INV_SQRT_2PI

    @staticmethod
    def _big_phi(x: jax.Array) -> jax.Array:
        return 0.5 * (1 + erf(x * CONST_INV_SQRT_2))

    @staticmethod
    def _inv_big_phi(x: jax.Array) -> jax.Array:
        return CONST_SQRT_2 * erfinv(2 * x - 1)

    def cdf(self, value: jax.Array) -> jax.Array:
        return jnp.clip((self._big_phi(value) - self._big_phi_a) / self._Z, 0, 1)

    def icdf(self, value: jax.Array) -> jax.Array:
        return self._inv_big_phi(self._big_phi_a + value * self._Z)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return CONST_LOG_INV_SQRT_2PI - self._log_Z - (value**2) * 0.5

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + self.a.shape
        p = jax.random.uniform(
            key, shape, dtype=self.a.dtype, minval=self._dtype_min_gt_0, maxval=self._dtype_max_lt_1
        )
        return self.icdf(p)

    rsample = sample

    def entropy(self) -> jax.Array:
        return self._entropy


class TruncatedNormal(TruncatedStandardNormal):
    """Truncated normal on [a, b] with location/scale
    (reference: sheeprl/utils/distribution.py:116-147)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, a: jax.Array, b: jax.Array):
        self.loc, self.scale, a, b = jnp.broadcast_arrays(
            jnp.asarray(loc), jnp.asarray(scale), jnp.asarray(a), jnp.asarray(b)
        )
        super().__init__((a - self.loc) / self.scale, (b - self.loc) / self.scale)
        self._log_scale = jnp.log(self.scale)
        self._mean = self._mean * self.scale + self.loc
        self._variance = self._variance * self.scale**2
        self._entropy = self._entropy + self._log_scale

    def _to_std_rv(self, value: jax.Array) -> jax.Array:
        return (value - self.loc) / self.scale

    def _from_std_rv(self, value: jax.Array) -> jax.Array:
        return value * self.scale + self.loc

    def cdf(self, value: jax.Array) -> jax.Array:
        return super().cdf(self._to_std_rv(value))

    def icdf(self, value: jax.Array) -> jax.Array:
        return self._from_std_rv(super().icdf(value))

    def log_prob(self, value: jax.Array) -> jax.Array:
        return super().log_prob(self._to_std_rv(value)) - self._log_scale


# --------------------------------------------------- symlog/mse "losses"
class SymlogDistribution:
    """MSE/abs distance in symlog space posing as a distribution
    (reference: sheeprl/utils/distribution.py:152-193; danijar jaxutils)."""

    def __init__(
        self,
        mode: jax.Array,
        dims: int,
        dist: str = "mse",
        agg: str = "sum",
        tol: float = 1e-8,
    ):
        self._mode = mode
        # dims=0 reduces ALL axes: torch's sum(dim=()) collapses everything,
        # and the reference relies on that default (distribution.py:162).
        self._dims = tuple(-x for x in range(1, dims + 1)) if dims else None
        self._dist = dist
        self._agg = agg
        self._tol = tol

    @property
    def mode(self) -> jax.Array:
        return symexp(self._mode)

    @property
    def mean(self) -> jax.Array:
        return symexp(self._mode)

    def log_prob(self, value: jax.Array) -> jax.Array:
        assert self._mode.shape == value.shape, (self._mode.shape, value.shape)
        if self._dist == "mse":
            distance = (self._mode - symlog(value)) ** 2
        elif self._dist == "abs":
            distance = jnp.abs(self._mode - symlog(value))
        else:
            raise NotImplementedError(self._dist)
        distance = jnp.where(distance < self._tol, 0, distance)
        if self._agg == "mean":
            loss = distance.mean(self._dims)
        elif self._agg == "sum":
            loss = distance.sum(self._dims)
        else:
            raise NotImplementedError(self._agg)
        return -loss


class MSEDistribution:
    """Plain MSE posing as a distribution
    (reference: sheeprl/utils/distribution.py:196-221)."""

    def __init__(self, mode: jax.Array, dims: int, agg: str = "sum"):
        self._mode = mode
        self._dims = tuple(-x for x in range(1, dims + 1)) if dims else None
        self._agg = agg

    @property
    def mode(self) -> jax.Array:
        return self._mode

    @property
    def mean(self) -> jax.Array:
        return self._mode

    def log_prob(self, value: jax.Array) -> jax.Array:
        assert self._mode.shape == value.shape, (self._mode.shape, value.shape)
        distance = (self._mode - value) ** 2
        if self._agg == "mean":
            loss = distance.mean(self._dims)
        elif self._agg == "sum":
            loss = distance.sum(self._dims)
        else:
            raise NotImplementedError(self._agg)
        return -loss


# ------------------------------------------------------- two-hot critic
class TwoHotEncodingDistribution:
    """Two-hot categorical over symlog-spaced bins; DV3 reward/critic heads
    (reference: sheeprl/utils/distribution.py:224-276)."""

    def __init__(
        self,
        logits: jax.Array,
        dims: int = 0,
        low: int = -20,
        high: int = 20,
        transfwd: Callable[[jax.Array], jax.Array] = symlog,
        transbwd: Callable[[jax.Array], jax.Array] = symexp,
    ):
        self.logits = logits
        self.probs = jax.nn.softmax(logits, axis=-1)
        self.dims = tuple(-x for x in range(1, dims + 1)) if dims else None
        self.bins = jnp.linspace(low, high, logits.shape[-1], dtype=logits.dtype)
        self.low = low
        self.high = high
        self.transfwd = transfwd
        self.transbwd = transbwd

    @property
    def mean(self) -> jax.Array:
        return self.transbwd((self.probs * self.bins).sum(axis=self.dims, keepdims=True))

    @property
    def mode(self) -> jax.Array:
        return self.transbwd((self.probs * self.bins).sum(axis=self.dims, keepdims=True))

    def log_prob(self, x: jax.Array) -> jax.Array:
        x = self.transfwd(x)
        nbins = self.bins.shape[0]
        below = (self.bins <= x).astype(jnp.int32).sum(axis=-1, keepdims=True) - 1
        above = jnp.minimum(below + 1, nbins - 1)
        below = jnp.maximum(below, 0)

        equal = below == above
        dist_to_below = jnp.where(equal, 1, jnp.abs(self.bins[below] - x))
        dist_to_above = jnp.where(equal, 1, jnp.abs(self.bins[above] - x))
        total = dist_to_below + dist_to_above
        weight_below = dist_to_above / total
        weight_above = dist_to_below / total
        target = (
            jax.nn.one_hot(below, nbins, dtype=x.dtype) * weight_below[..., None]
            + jax.nn.one_hot(above, nbins, dtype=x.dtype) * weight_above[..., None]
        ).squeeze(-2)
        log_pred = self.logits - jax.scipy.special.logsumexp(self.logits, axis=-1, keepdims=True)
        return (target * log_pred).sum(axis=self.dims)


# ----------------------------------------------------- one-hot categorical
class OneHotCategorical:
    """One-hot categorical over the last axis
    (reference: OneHotCategoricalValidateArgs, distribution.py:281-384)."""

    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        if (logits is None) == (probs is None):
            raise ValueError("Exactly one of `logits` or `probs` must be specified")
        if logits is None:
            probs = probs / probs.sum(axis=-1, keepdims=True)
            eps = jnp.finfo(probs.dtype).tiny
            logits = jnp.log(jnp.clip(probs, eps, None))
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mean(self) -> jax.Array:
        return self.probs

    @property
    def mode(self) -> jax.Array:
        p = self.probs
        return jax.nn.one_hot(jnp.argmax(p, axis=-1), p.shape[-1], dtype=p.dtype)

    @property
    def variance(self) -> jax.Array:
        p = self.probs
        return p * (1 - p)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        idx = jax.random.categorical(key, self.logits, shape=tuple(sample_shape) + self.logits.shape[:-1])
        return jax.nn.one_hot(idx, self.logits.shape[-1], dtype=self.logits.dtype)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return (value * self.logits).sum(axis=-1)

    def entropy(self) -> jax.Array:
        p = self.probs
        plogp = jnp.where(p > 0, p * self.logits, 0.0)
        return -plogp.sum(axis=-1)


class OneHotCategoricalStraightThrough(OneHotCategorical):
    """Straight-through gradient sampling: forward a hard one-hot, backward
    the probs gradient (reference: distribution.py:387-401)."""

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        samples = self.sample(key, sample_shape)
        probs = self.probs
        return samples + (probs - jax.lax.stop_gradient(probs))


# --------------------------------------------------------------- bernoulli
class BernoulliSafeMode:
    """Bernoulli whose mode is p > 0.5 (reference: distribution.py:409-416;
    used by the Dreamer continue head)."""

    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        if (logits is None) == (probs is None):
            raise ValueError("Exactly one of `logits` or `probs` must be specified")
        if logits is None:
            eps = jnp.finfo(probs.dtype).tiny
            logits = jnp.log(jnp.clip(probs, eps, None)) - jnp.log(jnp.clip(1 - probs, eps, None))
        self.logits = logits

    @property
    def probs(self) -> jax.Array:
        return jax.nn.sigmoid(self.logits)

    @property
    def mean(self) -> jax.Array:
        return self.probs

    @property
    def mode(self) -> jax.Array:
        p = self.probs
        return (p > 0.5).astype(p.dtype)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        p = self.probs
        u = jax.random.uniform(key, tuple(sample_shape) + p.shape, dtype=p.dtype)
        return (u < p).astype(p.dtype)

    def log_prob(self, value: jax.Array) -> jax.Array:
        # -BCEWithLogits: value*logsigmoid(l) + (1-value)*logsigmoid(-l)
        return value * jax.nn.log_sigmoid(self.logits) + (1 - value) * jax.nn.log_sigmoid(-self.logits)

    def entropy(self) -> jax.Array:
        p = self.probs
        return -(
            jnp.where(p > 0, p * jax.nn.log_sigmoid(self.logits), 0.0)
            + jnp.where(p < 1, (1 - p) * jax.nn.log_sigmoid(-self.logits), 0.0)
        )


# --------------------------------------------------------------------- kl
def kl_divergence(p, q) -> jax.Array:
    """KL(p||q) for the pairs the algorithms need (reference registers
    cat-cat at distribution.py:404-406; normal-normal via torch)."""
    if isinstance(p, Independent) and isinstance(q, Independent):
        if p.ndims != q.ndims:
            raise ValueError("Independent KL requires matching event ndims")
        return p._reduce(kl_divergence(p.base, q.base))
    if isinstance(p, OneHotCategorical) and isinstance(q, OneHotCategorical):
        probs = p.probs
        plogp_m_logq = jnp.where(probs > 0, probs * (p.logits - q.logits), 0.0)
        return plogp_m_logq.sum(axis=-1)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    raise NotImplementedError(f"KL not implemented for {type(p).__name__} || {type(q).__name__}")


# ----------------------------------------------------------------- unimix
def uniform_mix(logits: jax.Array, unimix: float) -> jax.Array:
    """Mix `unimix` of a uniform into the categorical over the last axis and
    return the new logits (reference: DreamerV3 RSSM._uniform_mix,
    sheeprl/algos/dreamer_v3/agent.py:437-449; 1% by default)."""
    if unimix > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        uniform = jnp.ones_like(probs) / probs.shape[-1]
        probs = (1 - unimix) * probs + unimix * uniform
        logits = jnp.log(probs)
    return logits
