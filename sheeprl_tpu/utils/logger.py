"""Experiment loggers.

Parity with the reference logger layer (sheeprl/utils/logger.py:12-89):
rank-0-only logger creation, versioned run directories, and log-dir sharing
across processes. TensorBoard writing uses tensorboardX; the multi-process
log-dir broadcast uses jax.experimental.multihost_utils instead of a
TorchCollective gloo group (DCN-level host sync, no NCCL/gloo).
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.config.instantiate import instantiate


class TensorBoardLogger:
    """Thin tensorboardX wrapper exposing the log/log_dict/log_hyperparams
    surface the algorithms use (reference calls fabric.log/log_dict)."""

    def __init__(self, root_dir: str, run_name: str):
        self.root_dir = root_dir
        self.run_name = run_name
        self._log_dir = _versioned_dir(os.path.join(root_dir, run_name))
        self._writer = None

    @property
    def log_dir(self) -> str:
        return self._log_dir

    @property
    def writer(self):
        if self._writer is None:
            from tensorboardX import SummaryWriter

            os.makedirs(self._log_dir, exist_ok=True)
            self._writer = SummaryWriter(self._log_dir)
        return self._writer

    def log(self, name: str, value: Any, step: int) -> None:
        self.writer.add_scalar(name, float(np.asarray(value)), step)

    def log_dict(self, metrics: Dict[str, Any], step: int) -> None:
        for k, v in metrics.items():
            self.log(k, v, step)

    def log_hyperparams(self, cfg: Dict[str, Any]) -> None:
        os.makedirs(self._log_dir, exist_ok=True)
        try:
            with open(os.path.join(self._log_dir, "hparams.json"), "w") as fp:
                json.dump(cfg, fp, default=str, indent=2)
        except TypeError:  # pragma: no cover - unserializable config values
            pass

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


def _versioned_dir(save_dir: str) -> str:
    """logs/.../run_name/version_N with N = max existing + 1
    (reference: sheeprl/utils/logger.py:66-85)."""
    try:
        existing = [
            int(d.split("_")[1])
            for d in os.listdir(save_dir)
            if d.startswith("version_") and os.path.isdir(os.path.join(save_dir, d))
        ]
    except OSError:
        existing = []
    version = max(existing) + 1 if existing else 0
    return os.path.join(save_dir, f"version_{version}")


def get_logger(runtime, cfg: Dict[str, Any]) -> Optional[TensorBoardLogger]:
    """Rank-0-only logger instantiation (reference: logger.py:12-38)."""
    if not runtime.is_global_zero or cfg.metric.log_level <= 0:
        return None
    target = str(cfg.metric.logger.get("_target_", ""))
    if "tensorboard" in target.lower():
        root_dir = os.path.join(cfg.log_root, cfg.root_dir)
        if root_dir != cfg.metric.logger.root_dir:
            warnings.warn(
                "The specified root directory for the TensorBoardLogger is different from the experiment one, "
                "so the logger one will be ignored and replaced with the experiment root directory",
                UserWarning,
            )
        cfg.metric.logger.root_dir = root_dir
        cfg.metric.logger.run_name = cfg.run_name
    return instantiate(cfg.metric.logger)


def get_log_dir(runtime, root_dir: str, run_name: str, share: bool = True, logger=None) -> str:
    """Create (rank-0) and share the run's log directory
    (reference: logger.py:41-89)."""
    import jax

    log_dir: Optional[str] = None
    if runtime.is_global_zero:
        if logger is not None and logger.log_dir is not None:
            log_dir = logger.log_dir
        else:
            log_dir = _versioned_dir(os.path.join("logs", "runs", root_dir, run_name))
        os.makedirs(log_dir, exist_ok=True)
    if jax.process_count() > 1 and share:
        log_dir = _broadcast_str(log_dir)
    return log_dir


def _broadcast_str(value: Optional[str]) -> str:
    """Broadcast a string from process 0 to all processes via a fixed-size
    uint8 buffer (multihost_utils broadcasts arrays, not objects)."""
    import jax
    from jax.experimental import multihost_utils

    max_len = 4096
    buf = np.zeros(max_len, np.uint8)
    if jax.process_index() == 0 and value is not None:
        raw = value.encode()[:max_len]
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    out = np.asarray(out).tobytes().rstrip(b"\x00")
    return out.decode()
