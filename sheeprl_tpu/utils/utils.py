"""Host-side helpers shared across the framework.

Capability parity with the reference's ``sheeprl/utils/utils.py`` (dotdict,
Ratio replay-ratio controller, polynomial_decay, config snapshotting), built
for a JAX/TPU runtime: everything here runs on the host and never traces.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Mapping

import yaml


class dotdict(dict):
    """A dictionary supporting dot notation access and recursive wrapping.

    Mirrors the runtime config object of the reference (sheeprl/utils/utils.py:34-60):
    after composition the config becomes a plain dict subclass that algorithms may
    mutate freely.
    """

    __getattr__ = dict.get
    __setattr__ = dict.__setitem__
    __delattr__ = dict.__delitem__

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in self.items():
            if isinstance(v, dict) and not isinstance(v, dotdict):
                self[k] = dotdict(v)

    def __getstate__(self):
        return dict(self)

    def __setstate__(self, state):
        self.update(state)

    def as_dict(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.items():
            out[k] = v.as_dict() if isinstance(v, dotdict) else v
        return out


def get_by_path(cfg: Mapping[str, Any], path: str, default: Any = None) -> Any:
    """Fetch ``a.b.c`` style path from a nested mapping."""
    node: Any = cfg
    for part in path.split("."):
        if not isinstance(node, Mapping) or part not in node:
            return default
        node = node[part]
    return node


def set_by_path(cfg: Dict[str, Any], path: str, value: Any) -> None:
    """Set ``a.b.c`` style path in a nested dict, creating intermediate dicts."""
    parts = path.split(".")
    node = cfg
    for part in parts[:-1]:
        nxt = node.get(part)
        if not isinstance(nxt, dict):
            nxt = dotdict() if isinstance(cfg, dotdict) else {}
            node[part] = nxt
        node = nxt
    node[parts[-1]] = value


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Polynomial decay schedule (reference: sheeprl/utils/utils.py:133-144)."""
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


class Ratio:
    """Replay-ratio controller: given a monotonically increasing policy-step
    counter, return how many gradient steps to run so that the long-run ratio
    gradient_steps / policy_steps approaches ``ratio``.

    Semantics match the reference (sheeprl/utils/utils.py:259-300), which in
    turn follows Hafner's DreamerV3 `when.Ratio`.
    """

    def __init__(self, ratio: float, pretrain_steps: int = 0):
        if pretrain_steps < 0:
            raise ValueError(f"'pretrain_steps' must be non-negative, got {pretrain_steps}")
        if ratio < 0:
            raise ValueError(f"'ratio' must be non-negative, got {ratio}")
        self._pretrain_steps = pretrain_steps
        self._ratio = ratio
        self._prev: float | None = None

    def __call__(self, step: int) -> int:
        if self._ratio == 0:
            return 0
        if self._prev is None:
            self._prev = step
            repeats = int(step * self._ratio)
            if self._pretrain_steps > 0:
                if step < self._pretrain_steps:
                    warnings.warn(
                        "The number of pretrain steps is greater than the number of current steps. "
                        f"This could lead to a higher ratio than the one specified ({self._ratio}). "
                        "Setting the 'pretrain_steps' equal to the number of current steps."
                    )
                    self._pretrain_steps = step
                repeats = int(self._pretrain_steps * self._ratio)
            return repeats
        repeats = int((step - self._prev) * self._ratio)
        self._prev += repeats / self._ratio
        return repeats

    def state_dict(self) -> Dict[str, Any]:
        return {"_ratio": self._ratio, "_prev": self._prev, "_pretrain_steps": self._pretrain_steps}

    def load_state_dict(self, state: Mapping[str, Any]) -> "Ratio":
        self._ratio = state["_ratio"]
        self._prev = state["_prev"]
        self._pretrain_steps = state["_pretrain_steps"]
        return self


def save_configs(cfg: dotdict, log_dir: str) -> None:
    """Snapshot the resolved config as YAML in the run directory.

    This file is the contract for resume/eval (reference: save_configs,
    sheeprl/utils/utils.py:255).
    """
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, "config.yaml"), "w") as fp:
        yaml.safe_dump(cfg.as_dict() if isinstance(cfg, dotdict) else dict(cfg), fp, sort_keys=False)


def load_yaml(path: str) -> Dict[str, Any]:
    with open(path) as fp:
        return yaml.safe_load(fp) or {}


def print_config(cfg: Mapping[str, Any], fields=("algo", "buffer", "checkpoint", "env", "fabric", "metric")) -> None:
    """Print the selected top-level config sections as YAML."""
    for field in fields:
        section = cfg.get(field)
        if section is None:
            continue
        print(f"── {field} " + "─" * max(0, 60 - len(field)))
        body = section.as_dict() if isinstance(section, dotdict) else section
        print(yaml.safe_dump(body, sort_keys=False, default_flow_style=None).rstrip())
