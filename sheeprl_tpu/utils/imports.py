"""Optional-dependency availability flags (reference: sheeprl/utils/imports.py).

Each env family ships as an import-gated module: the flag is checked at module
import time so a missing simulator fails fast with an actionable message, and
`register_all()` skips the family without breaking the rest of the framework.
"""

from __future__ import annotations

import importlib.util


def module_available(name: str) -> bool:
    """True when ``name`` can be imported (checked without importing it)."""
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


_IS_ALE_AVAILABLE = module_available("ale_py")
_IS_CRAFTER_AVAILABLE = module_available("crafter")
_IS_DIAMBRA_AVAILABLE = module_available("diambra")
_IS_DIAMBRA_ARENA_AVAILABLE = module_available("diambra.arena")
_IS_DMC_AVAILABLE = module_available("dm_control")
_IS_MINEDOJO_AVAILABLE = module_available("minedojo")
_IS_MINERL_AVAILABLE = module_available("minerl")
_IS_MLFLOW_AVAILABLE = module_available("mlflow")
_IS_SUPER_MARIO_BROS_AVAILABLE = module_available("gym_super_mario_bros")


def require(flag: bool, package: str, extra: str) -> None:
    """Raise a uniform gate error for a missing optional dependency."""
    if not flag:
        raise ModuleNotFoundError(
            f"The '{package}' package is required for this feature but is not "
            f"installed. Install it (e.g. `pip install {extra}`) to use it."
        )
