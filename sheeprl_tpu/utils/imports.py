"""Optional-dependency availability flags (reference: sheeprl/utils/imports.py).

Each env family ships as an import-gated module: the flag is checked at module
import time so a missing simulator fails fast with an actionable message, and
`register_all()` skips the family without breaking the rest of the framework.
"""

from __future__ import annotations

import importlib.util


def module_available(name: str) -> bool:
    """True when ``name`` can be imported (checked without importing it)."""
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


_IS_ALE_AVAILABLE = module_available("ale_py")
_IS_CRAFTER_AVAILABLE = module_available("crafter")
_IS_DIAMBRA_AVAILABLE = module_available("diambra")
_IS_DIAMBRA_ARENA_AVAILABLE = module_available("diambra.arena")
_IS_DMC_AVAILABLE = module_available("dm_control")
_IS_MINEDOJO_AVAILABLE = module_available("minedojo")
_IS_MINERL_AVAILABLE = module_available("minerl")
_IS_MLFLOW_AVAILABLE = module_available("mlflow")
_IS_SUPER_MARIO_BROS_AVAILABLE = module_available("gym_super_mario_bros")


_DMC_RUNTIME_REASON: "str | None | type[Ellipsis]" = ...


def dmc_runtime_unusable_reason() -> "str | None":
    """``None`` when a dm_control env can actually be constructed in this
    process, else the capability error. Import availability alone is not
    enough: dm_control can be installed yet unusable (e.g. headless
    containers where ``MUJOCO_GL=egl`` finds no EGL driver and mujoco's GL
    import fails). Probed once per process, with the cheapest vector-only
    task."""
    global _DMC_RUNTIME_REASON
    if not _IS_DMC_AVAILABLE:
        return "dm_control not installed"
    if _DMC_RUNTIME_REASON is ...:
        try:
            from sheeprl_tpu.envs.dmc import DMCWrapper

            env = DMCWrapper("cartpole", "balance", from_pixels=False, from_vectors=True, seed=0)
            env.reset(seed=0)
            env.close()
            _DMC_RUNTIME_REASON = None
        except Exception as e:  # capability probe: any failure means unusable
            _DMC_RUNTIME_REASON = f"dm_control unusable here: {type(e).__name__}: {e}"
    return _DMC_RUNTIME_REASON


def require(flag: bool, package: str, extra: str) -> None:
    """Raise a uniform gate error for a missing optional dependency."""
    if not flag:
        raise ModuleNotFoundError(
            f"The '{package}' package is required for this feature but is not "
            f"installed. Install it (e.g. `pip install {extra}`) to use it."
        )
