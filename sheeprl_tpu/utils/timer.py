"""Phase wall-clock timers.

Parity with the reference `timer` ContextDecorator
(sheeprl/utils/timer.py:16-84): accumulates elapsed seconds per key into a
process-global store, with a global disable flag, compute() and reset().
On TPU the caller is responsible for bounding timed regions with
`jax.block_until_ready` where async dispatch would make wall-clock lie
(the algorithms do this around their jitted update calls).
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, ClassVar, Dict, Optional


class TimerError(Exception):
    """A custom exception used to report errors in use of timer class."""


class timer(ContextDecorator):
    disabled: ClassVar[bool] = False
    timers: ClassVar[Dict[str, float]] = {}
    _start_times: ClassVar[Dict[str, float]] = {}

    def __init__(self, name: str, metric: Any = None, **kwargs: Any) -> None:
        # `metric` accepted for reference-call-site parity (SumMetric etc.);
        # accumulation is always a float sum here.
        self.name = name

    def start(self) -> None:
        if self.disabled:
            return
        if self.name in type(self)._start_times:
            raise TimerError(f"Timer '{self.name}' is running. Use .stop() to stop it")
        type(self)._start_times[self.name] = time.perf_counter()

    def stop(self) -> float:
        if self.disabled:
            return 0.0
        if self.name not in type(self)._start_times:
            raise TimerError(f"Timer '{self.name}' is not running. Use .start() to start it")
        elapsed = time.perf_counter() - type(self)._start_times.pop(self.name)
        type(self).timers[self.name] = type(self).timers.get(self.name, 0.0) + elapsed
        return elapsed

    def __enter__(self) -> "timer":
        self.start()
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.stop()

    @classmethod
    def compute(cls) -> Dict[str, float]:
        return dict(cls.timers) if not cls.disabled else {}

    @classmethod
    def reset(cls) -> None:
        cls.timers = {}
        cls._start_times = {}
