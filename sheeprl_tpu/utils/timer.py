"""Phase wall-clock timers.

Parity with the reference `timer` ContextDecorator
(sheeprl/utils/timer.py:16-84): accumulates elapsed seconds per key into a
process-global store, with a global disable flag, compute() and reset().

Two departures from the reference:

- **Reentrant**: each key keeps a *stack* of start times, so nested or
  re-entered use of the same key (a decorated helper called inside a
  ``with timer(key)`` block, recursive phases) accumulates correctly
  instead of raising ``TimerError`` mid-run. stop() without a matching
  start() still raises.
- **Span emission**: every stop also emits the measured region as a span
  into the process-wide telemetry tracer (a no-op unless a run installed
  one), so ``timer.compute()`` and the exported trace agree by
  construction.

On TPU the caller is responsible for bounding timed regions where async
dispatch would make wall-clock lie; the train loops do this through
StepTimer's single per-interval block (sheeprl_tpu/telemetry/step_timer.py),
which credits the block back into the phase total via :meth:`timer.add`.
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, ClassVar, Dict, List


class TimerError(Exception):
    """A custom exception used to report errors in use of timer class."""


class timer(ContextDecorator):
    disabled: ClassVar[bool] = False
    timers: ClassVar[Dict[str, float]] = {}
    _start_times: ClassVar[Dict[str, List[float]]] = {}

    def __init__(self, name: str, metric: Any = None, **kwargs: Any) -> None:
        # `metric` accepted for reference-call-site parity (SumMetric etc.);
        # accumulation is always a float sum here.
        self.name = name

    def start(self) -> None:
        if self.disabled:
            return
        type(self)._start_times.setdefault(self.name, []).append(time.perf_counter())

    def stop(self) -> float:
        if self.disabled:
            return 0.0
        stack = type(self)._start_times.get(self.name)
        if not stack:
            raise TimerError(f"Timer '{self.name}' is not running. Use .start() to start it")
        started = stack.pop()
        if not stack:
            del type(self)._start_times[self.name]
        elapsed = time.perf_counter() - started
        type(self).timers[self.name] = type(self).timers.get(self.name, 0.0) + elapsed
        # Keep the trace and compute() in agreement: the stopped region is
        # also a span on the telemetry timeline (no-op tracer by default).
        from sheeprl_tpu.telemetry.tracer import current

        current().add_span(self.name, "timer", started, elapsed)
        return elapsed

    def __enter__(self) -> "timer":
        self.start()
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.stop()

    @classmethod
    def add(cls, name: str, seconds: float) -> None:
        """Credit externally-measured seconds to a key (StepTimer's
        per-interval bounding block lands here so phase sums stay true)."""
        if cls.disabled:
            return
        cls.timers[name] = cls.timers.get(name, 0.0) + float(seconds)

    @classmethod
    def compute(cls) -> Dict[str, float]:
        return dict(cls.timers) if not cls.disabled else {}

    @classmethod
    def reset(cls) -> None:
        cls.timers = {}
        cls._start_times = {}
