"""Metric aggregation (host-side, numpy).

API parity with the reference's torchmetrics-backed aggregator
(sheeprl/utils/metric.py:17-196) without the torch dependency: metrics are
tiny host accumulators updated with numbers/arrays (jax.Array values are
pulled to host — call sites pass already-computed scalars, so this never
forces a device sync inside a hot loop).

`sync_on_compute` has the reference's torchmetrics semantics: when True and
more than one process is running, `compute()` first all-gathers each
metric's accumulator state over DCN (`multihost_utils.process_allgather`)
and reduces across ranks — MeanMetric returns the global mean (sum of sums
over sum of counts), Sum the global sum, Max/Min the global extrema. Like
torchmetrics' sync, this is a COLLECTIVE: every process must call compute()
on the same metrics in the same order.
"""

from __future__ import annotations

import warnings
from math import isnan
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class MetricAggregatorException(Exception):
    """A custom exception used to report errors in use of the aggregator."""


class Metric:
    """Minimal metric interface: update / compute / reset.

    Metrics registered in a :class:`MetricAggregator` may additionally
    implement the `_state()`/`_reduce()` protocol below so the aggregator
    can batch every metric's cross-rank sync into ONE DCN all-gather; a
    metric that only implements `compute()` still works — the aggregator
    falls back to calling it directly (unbatched, and synced only if the
    metric's own compute() handles it).
    """

    def __init__(self, sync_on_compute: bool = False):
        self.sync_on_compute = sync_on_compute
        self.reset()

    def update(self, value: Any) -> None:
        raise NotImplementedError

    def compute(self) -> float:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    @staticmethod
    def _to_float(value: Any) -> float:
        arr = np.asarray(value, dtype=np.float64)
        return float(arr.mean()) if arr.ndim > 0 else float(arr)

    # -- cross-rank reduction protocol -----------------------------------
    # `_state()` exposes the accumulator as a flat float tuple; `_reduce()`
    # folds one such tuple per rank into the final value. compute() is
    # written in terms of these so MetricAggregator can gather EVERY
    # metric's state in one batched DCN all-gather instead of one
    # collective per metric.
    def _state(self) -> Tuple[float, ...]:
        raise NotImplementedError

    def _reduce(self, states: List[Tuple[float, ...]]) -> float:
        raise NotImplementedError

    def _all_states(self) -> List[Tuple[float, ...]]:
        """Per-rank accumulator states: `[self._state()]` alone when sync is
        off or the run is single-process, otherwise one tuple per process
        from a DCN all-gather (the reference's torchmetrics dist-sync
        analog)."""
        state = self._state()
        if not self.sync_on_compute:
            return [state]
        import jax

        if jax.process_count() <= 1:
            return [state]
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray(state, np.float64))
        return [tuple(row) for row in np.asarray(gathered).reshape(jax.process_count(), -1)]

    def compute(self) -> float:
        return self._reduce(self._all_states())


class MeanMetric(Metric):
    def update(self, value: Any) -> None:
        arr = np.asarray(value, dtype=np.float64).reshape(-1)
        self._sum += float(arr.sum())
        self._count += arr.size

    def _state(self) -> Tuple[float, ...]:
        return (self._sum, float(self._count))

    def _reduce(self, states: List[Tuple[float, ...]]) -> float:
        total = sum(s for s, _ in states)
        count = sum(c for _, c in states)
        return total / count if count else float("nan")

    def reset(self) -> None:
        self._sum = 0.0
        self._count = 0


class SumMetric(Metric):
    def update(self, value: Any) -> None:
        self._sum += float(np.asarray(value, dtype=np.float64).sum())

    def _state(self) -> Tuple[float, ...]:
        return (self._sum,)

    def _reduce(self, states: List[Tuple[float, ...]]) -> float:
        return sum(s for (s,) in states)

    def reset(self) -> None:
        self._sum = 0.0


class MaxMetric(Metric):
    def update(self, value: Any) -> None:
        self._max = max(self._max, float(np.asarray(value, dtype=np.float64).max()))

    def _state(self) -> Tuple[float, ...]:
        return (self._max,)

    def _reduce(self, states: List[Tuple[float, ...]]) -> float:
        return max(m for (m,) in states)

    def reset(self) -> None:
        self._max = float("-inf")


class MinMetric(Metric):
    def update(self, value: Any) -> None:
        self._min = min(self._min, float(np.asarray(value, dtype=np.float64).min()))

    def _state(self) -> Tuple[float, ...]:
        return (self._min,)

    def _reduce(self, states: List[Tuple[float, ...]]) -> float:
        return min(m for (m,) in states)

    def reset(self) -> None:
        self._min = float("inf")


class LastMetric(Metric):
    def update(self, value: Any) -> None:
        self._last = self._to_float(value)

    def _state(self) -> Tuple[float, ...]:
        return (self._last,)

    def _reduce(self, states: List[Tuple[float, ...]]) -> float:
        # Cross-rank reduction: mean of the ranks that observed a value.
        lasts = [v for (v,) in states if not isnan(v)]
        return float(np.mean(lasts)) if lasts else float("nan")

    def reset(self) -> None:
        self._last = float("nan")


class MetricAggregator:
    """Aggregate named metrics (reference: sheeprl/utils/metric.py:17-143).

    The class-level `disabled` flag mirrors the reference's global disable
    (set from `metric.log_level == 0` at startup); `compute()` drops NaN
    results the same way.
    """

    disabled: bool = False

    def __init__(self, metrics: Optional[Dict[str, Metric]] = None, raise_on_missing: bool = False):
        self.metrics: Dict[str, Metric] = metrics if metrics is not None else {}
        self._raise_on_missing = raise_on_missing
        self._warned_local_fallback: set = set()

    def __iter__(self):
        return iter(self.metrics.keys())

    def __contains__(self, name: str) -> bool:
        return name in self.metrics

    def add(self, name: str, metric: Metric) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            self.metrics[name] = metric
        elif self._raise_on_missing:
            raise MetricAggregatorException(f"Metric {name} already exists")
        else:
            warnings.warn(f"The key '{name}' is already in the metric aggregator. Nothing will be added.", UserWarning)

    def update(self, name: str, value: Any) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            if self._raise_on_missing:
                raise MetricAggregatorException(f"Metric {name} does not exist")
            warnings.warn(f"The key '{name}' is missing from the metric aggregator. Nothing will be added.", UserWarning)
            return
        self.metrics[name].update(value)

    def pop(self, name: str) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            if self._raise_on_missing:
                raise MetricAggregatorException(f"Metric {name} does not exist")
            warnings.warn(f"The key '{name}' is missing from the metric aggregator. Nothing will be popped.", UserWarning)
        self.metrics.pop(name, None)

    def reset(self) -> None:
        if self.disabled:
            return
        for metric in self.metrics.values():
            metric.reset()

    def to(self, device: Any = None) -> "MetricAggregator":
        # Device-placement no-op: metrics live on host (kept for API parity).
        return self

    def compute(self) -> Dict[str, float]:
        """Reduced values of every metric, NaN results dropped.

        When any metric has sync_on_compute in a multi-process run this is a
        COLLECTIVE — every rank must call it at the same point — but the
        whole aggregator costs ONE batched DCN all-gather, not one per
        metric."""
        reduced: Dict[str, float] = {}
        if self.disabled:
            return reduced
        synced_rows: Dict[str, List[Tuple[float, ...]]] = {}
        synced = {k: m for k, m in self.metrics.items() if m.sync_on_compute}
        if synced:
            import jax

            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                states = {}
                for k, m in synced.items():
                    try:
                        states[k] = np.asarray(m._state(), np.float64)
                    except NotImplementedError:
                        # Falls back to m.compute() below — which is
                        # RANK-LOCAL despite sync_on_compute. Say so once
                        # per key instead of silently under-reporting.
                        if k not in self._warned_local_fallback:
                            self._warned_local_fallback.add(k)
                            warnings.warn(
                                f"Metric '{k}' requests sync_on_compute but implements only "
                                "update/compute/reset (no _state()); under multiple processes "
                                "its reported value is rank-local, not cross-rank reduced.",
                                UserWarning,
                            )
                gathered = multihost_utils.process_allgather(states)
                n = jax.process_count()
                synced_rows = {
                    k: [tuple(row) for row in np.asarray(v).reshape(n, -1)]
                    for k, v in gathered.items()
                }
        for k, v in self.metrics.items():
            if k in synced_rows:
                value = v._reduce(synced_rows[k])
            else:
                try:
                    value = v._reduce([v._state()])
                except NotImplementedError:
                    # A custom metric implementing only the documented minimal
                    # update/compute/reset interface.
                    value = v.compute()
            if isinstance(value, float) and isnan(value):
                continue
            reduced[k] = value
        return reduced

    def log_and_reset(self, logger, step: int) -> Dict[str, float]:
        """The per-iteration logging contract every algorithm shares:
        compute (a collective when sync_on_compute is on — EVERY rank calls
        this, which is exactly why the helper exists), reset, and write the
        reduced values through the rank-0 logger if there is one."""
        computed = self.compute()
        self.reset()
        if logger is not None:
            logger.log_dict(computed, step)
        return computed


class RankIndependentMetricAggregator:
    """Per-rank metric streams (reference: sheeprl/utils/metric.py:146-196).

    compute() returns the per-process values as a list indexed by process;
    on a single host that is a one-element list. Multi-host gathering uses
    jax.experimental.multihost_utils when more than one process is present.
    """

    def __init__(self, metrics: "Dict[str, Metric] | MetricAggregator") -> None:
        self._aggregator = metrics if isinstance(metrics, MetricAggregator) else MetricAggregator(metrics)
        for m in self._aggregator.metrics.values():
            m.sync_on_compute = False

    def update(self, name: str, value: Any) -> None:
        self._aggregator.update(name, value)

    def compute(self) -> List[Dict[str, float]]:
        computed = self._aggregator.compute()
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(
                {k: np.asarray(v, np.float64) for k, v in computed.items()}
            )
            n = jax.process_count()
            return [{k: float(np.asarray(v)[i]) for k, v in gathered.items()} for i in range(n)]
        return [computed]

    def to(self, device: Any = None) -> "RankIndependentMetricAggregator":
        return self

    def reset(self) -> None:
        self._aggregator.reset()
