"""Checkpoint write/read with atomic commit, manifests, and per-rank GC.

Parity with the reference CheckpointCallback (sheeprl/utils/callback.py:14-148):
state = model params + optimizer states + counters (+ algorithm extras such as
replay buffers), written at `<log_dir>/checkpoint/ckpt_<policy_step>_<rank>.ckpt`
with at most `keep_last` checkpoints retained — per rank, so a multi-rank run
never GCs another rank's newest snapshot.

Backend: Orbax `StandardCheckpointer` over a pure-numpy pytree — every jax
Array is pulled to host first so saves never hold device memory, and restores
return numpy leaves that algorithms re-shard themselves (the TPU equivalent of
torch's map_location). A checkpoint is a *directory* (Orbax layout), not a
single file; the `.ckpt` suffix is kept for reference-parity path printing.
Non-array leaves (ints, floats, strings, None) are pickled alongside in
`aux.pkl` because Orbax handles only array-like leaves.

Atomicity (the Podracer preemption model — arXiv:2104.06272 — assumes saves
survive a kill at ANY byte): the whole checkpoint is staged in a temp sibling
directory (`.tmp-*`, same filesystem), a `manifest.json` (schema version,
step, rank, leaf counts, content digests) is written and fsynced last, and the
directory is committed with a single `os.rename`. A kill mid-save leaves
either the previous snapshot intact or a `.tmp-*` orphan that
:func:`find_latest_valid_checkpoint` ignores — there is no observable state
where the old checkpoint is gone and the new one incomplete. Layout::

    ckpt_<step>_<rank>.ckpt/
        arrays/         # Orbax tree
        aux.pkl         # non-array leaves
        manifest.json   # written + fsynced last, inside the staging dir

Pre-manifest checkpoints (Orbax tree at the directory root) still load; they
are simply never considered *valid* by the resilience fallback scan.

Topology elasticity (the t5x recorded-shardings seam): the manifest also
records each device-backed leaf's ``NamedSharding`` — the PartitionSpec axis
names plus the mesh axis sizes it was saved under — keyed by the leaf's
normalized tree path. Since the payload itself is full host numpy (never a
shard), a run saved on an 8-device mesh restores *bit-compatibly* on 4 (or
1): :func:`place_with_recorded_shardings` replays each recorded spec against
the new mesh, dropping any axis that no longer divides (replicating that dim
instead), so the layout intent survives resizes and the values are untouched.
The ``shardings`` manifest key is optional — schema_version stays 1 and
pre-elastic readers/writers interoperate both ways.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import time
import uuid
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from sheeprl_tpu.core import chaos

_CKPT_RE = re.compile(r"ckpt_(\d+)_(\d+)\.ckpt$")
_TMP_PREFIX = ".tmp-"
_TRASH_PREFIX = ".trash-"
_STALE_TMP_S = 3600.0

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA_VERSION = 1

_ARRAY_TYPES = (np.ndarray, np.generic, jax.Array)

# Callables invoked with the final committed path after every successful
# save — how the PreemptionGuard learns about saves without every train loop
# having to thread a callback through its checkpoint block.
_POST_SAVE_HOOKS: List[Callable[[str], None]] = []


def register_post_save_hook(hook: Callable[[str], None]) -> None:
    _POST_SAVE_HOOKS.append(hook)


def unregister_post_save_hook(hook: Callable[[str], None]) -> None:
    try:
        _POST_SAVE_HOOKS.remove(hook)
    except ValueError:
        pass


def _split_state(tree: Any, path: str = ""):
    """Split a pytree into (array-only tree with None placeholders, aux dict
    of path->non-array leaf)."""
    aux: Dict[str, Any] = {}

    def walk(node: Any, prefix: str) -> Any:
        if isinstance(node, dict):
            return {k: walk(v, f"{prefix}/{k}") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v, f"{prefix}/{i}") for i, v in enumerate(node)]
            if hasattr(node, "_fields"):  # namedtuple (optax states)
                return type(node)(*walked)
            return tuple(walked) if isinstance(node, tuple) else walked
        if isinstance(node, _ARRAY_TYPES):
            return np.asarray(node)
        aux[prefix] = node
        return None

    return walk(tree, path), aux


def _merge_state(tree: Any, aux: Dict[str, Any], path: str = "") -> Any:
    def walk(node: Any, prefix: str) -> Any:
        if isinstance(node, dict):
            return {k: walk(v, f"{prefix}/{k}") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v, f"{prefix}/{i}") for i, v in enumerate(node)]
            if hasattr(node, "_fields"):
                return type(node)(*walked)
            return tuple(walked) if isinstance(node, tuple) else walked
        if node is None and prefix in aux:
            return aux[prefix]
        return node

    return walk(tree, path)


# ------------------------------------------------------------ fsync helpers
def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """fsync a directory so the rename that committed it is durable. Best
    effort — some filesystems refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ------------------------------------------------------------ digests
def _digest_arrays(arrays: Any) -> Tuple[str, int]:
    """sha256 over every array leaf (dtype+shape+bytes, flatten order) and
    the leaf count. Restore-side recomputation matches because Orbax
    round-trips numpy dtypes/shapes exactly and tree_leaves order is
    structure-stable."""
    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves(arrays)
    for leaf in leaves:
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest(), len(leaves)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fp:
        for block in iter(lambda: fp.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def parse_ckpt_name(ckpt_path: str) -> Optional[Tuple[int, int]]:
    """(policy_step, rank) from a `ckpt_<step>_<rank>.ckpt` path, else None."""
    m = _CKPT_RE.search(os.path.basename(ckpt_path))
    return (int(m.group(1)), int(m.group(2))) if m else None


def read_manifest(ckpt_path: str) -> Optional[Dict[str, Any]]:
    """Parse `manifest.json` from a checkpoint dir; None if absent/corrupt."""
    manifest_path = os.path.join(ckpt_path, MANIFEST_NAME)
    try:
        with open(manifest_path, "rb") as fp:
            manifest = json.load(fp)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def validate_checkpoint(ckpt_path: str, verify_digest: bool = False) -> bool:
    """True iff `ckpt_path` is a complete, committed checkpoint.

    Structural validation (default): manifest parses, schema is known, and
    the files it promises exist. With `verify_digest`, additionally rehash
    aux.pkl and reload + rehash every array leaf against the manifest —
    expensive, but catches bit rot, not just torn writes.
    """
    manifest = read_manifest(ckpt_path)
    if manifest is None:
        return False
    try:
        if int(manifest["schema_version"]) > MANIFEST_SCHEMA_VERSION:
            return False
        int(manifest["step"])
        int(manifest["rank"])
        leaf_count = int(manifest["leaf_count"])
    except (KeyError, TypeError, ValueError):
        return False
    arrays_dir = os.path.join(ckpt_path, "arrays")
    aux_file = os.path.join(ckpt_path, "aux.pkl")
    if not os.path.isdir(arrays_dir) or not os.path.isfile(aux_file):
        return False
    if not verify_digest:
        return True
    try:
        if _sha256_file(aux_file) != manifest.get("aux_sha256"):
            return False
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            arrays = ckptr.restore(os.path.abspath(arrays_dir))
        digest, n = _digest_arrays(arrays)
        return n == leaf_count and digest == manifest.get("digest")
    except Exception:  # noqa: BLE001 - any unreadable payload means invalid
        return False


def find_latest_valid_checkpoint(
    ckpt_dir: str, rank: Optional[int] = None, verify_digest: bool = False
) -> Optional[str]:
    """Newest checkpoint in `ckpt_dir` that passes validation, or None.

    Scans `ckpt_<step>_<rank>.ckpt` entries newest-step-first (optionally for
    one rank) and skips anything torn, truncated, or pre-manifest — the
    fallback path a preempted run resumes through when the most recent save
    was interrupted.
    """
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None  # racing a writer/GC that (re)moved the dir itself
    entries = []
    for name in names:
        m = _CKPT_RE.search(name)
        if not m:
            continue
        if rank is not None and int(m.group(2)) != rank:
            continue
        entries.append((int(m.group(1)), name))
    for _, name in sorted(entries, reverse=True):
        full = os.path.join(ckpt_dir, name)
        if validate_checkpoint(full, verify_digest=verify_digest):
            return full
    return None


@contextmanager
def atomic_dir_writer(final_path: str, fail_point: str = "checkpoint.before_commit") -> Iterator[str]:
    """Stage a directory payload, then commit it with a single ``os.rename``.

    Yields a ``.tmp-*`` sibling of ``final_path`` (same filesystem, so the
    rename is atomic); the caller writes the complete payload there. On
    normal exit the staging dir is fsynced and renamed into place — swapping
    through a ``.trash-*`` sibling when ``final_path`` already exists, so the
    old content stays reachable until the new one is committed. On any
    exception the staging dir is removed and nothing at ``final_path``
    changes. This is the commit discipline shared by checkpoints and policy
    artifacts; a kill at any byte leaves either the previous version or an
    orphan that :func:`_gc_stale_staging` reaps.
    """
    final_path = os.path.abspath(final_path)
    parent = os.path.dirname(final_path)
    basename = os.path.basename(final_path)
    os.makedirs(parent, exist_ok=True)
    staging = os.path.join(parent, f"{_TMP_PREFIX}{basename}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
    try:
        yield staging
        _fsync_dir(staging)
        chaos.maybe_fail(fail_point)
        if os.path.lexists(final_path):
            trash = os.path.join(parent, f"{_TRASH_PREFIX}{basename}-{uuid.uuid4().hex[:8]}")
            os.rename(final_path, trash)
            os.rename(staging, final_path)
            shutil.rmtree(trash, ignore_errors=True)
        else:
            os.rename(staging, final_path)
        _fsync_dir(parent)
    except BaseException:
        # A failed write must not leave the target half-written — it never
        # does (we only rename at the end) — but also should not leak the
        # staging dir on the *exception* path (a hard kill still can; see
        # _gc_stale_staging).
        shutil.rmtree(staging, ignore_errors=True)
        raise


def _gc_stale_staging(ckpt_dir: str) -> None:
    """Remove `.tmp-*` / `.trash-*` orphans left by killed saves, once old
    enough that no live writer can still own them."""
    now = time.time()
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return
    for name in names:
        if not (name.startswith(_TMP_PREFIX) or name.startswith(_TRASH_PREFIX)):
            continue
        full = os.path.join(ckpt_dir, name)
        try:
            if name.startswith(_TRASH_PREFIX) or now - os.path.getmtime(full) > _STALE_TMP_S:
                shutil.rmtree(full, ignore_errors=True)
        except OSError:
            continue


def save_checkpoint(
    ckpt_path: str,
    state: Dict[str, Any],
    keep_last: Optional[int] = None,
    *,
    step: Optional[int] = None,
    rank: Optional[int] = None,
) -> str:
    """Atomically write `state` (pytree) to `ckpt_path` and GC old
    checkpoints in the same directory down to `keep_last` per rank
    (reference: callback.py:30-38,144-148).

    The previous snapshot at `ckpt_path` (if any) stays on disk until the new
    one is fully staged and committed; a kill at any point leaves a valid
    prior state for :func:`find_latest_valid_checkpoint`.
    """
    import orbax.checkpoint as ocp

    from sheeprl_tpu.telemetry import tracer as tracer_mod

    ckpt_path = os.path.abspath(ckpt_path)
    parent = os.path.dirname(ckpt_path)
    basename = os.path.basename(ckpt_path)
    os.makedirs(parent, exist_ok=True)
    if step is None or rank is None:
        parsed = parse_ckpt_name(basename)
        step = step if step is not None else (parsed[0] if parsed else 0)
        rank = rank if rank is not None else (parsed[1] if parsed else 0)

    tracer = tracer_mod.current()
    start = time.perf_counter()
    chaos.maybe_fail("checkpoint.before_write")
    # Capture per-leaf shardings BEFORE the host pull erases them — the
    # manifest records layout intent; the payload stays full host numpy.
    recorded_shardings = _record_shardings(state)
    host_state = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, state
    )
    arrays, aux = _split_state(host_state)

    # Stage the full payload in a temp sibling (same filesystem, so the
    # final os.rename is atomic); atomic_dir_writer owns the commit/cleanup.
    with atomic_dir_writer(ckpt_path) as staging:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.join(staging, "arrays"), arrays)
        staging_aux = os.path.join(staging, "aux.pkl")
        # The aux payload may hold MemmapArrays (buffer-in-checkpoint): this
        # pickle is a durable reference to their backing files, so the
        # sources must relinquish deletion — declared via the scope rather
        # than as a side effect of any pickling (see data/memmap.py).
        from sheeprl_tpu.data.memmap import ownership_transfer_scope

        with open(staging_aux, "wb") as fp:
            with ownership_transfer_scope():
                pickle.dump(aux, fp)
            fp.flush()
            os.fsync(fp.fileno())
        chaos.maybe_fail("checkpoint.before_manifest")

        digest, leaf_count = _digest_arrays(arrays)
        manifest = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "step": int(step),
            "rank": int(rank),
            "leaf_count": leaf_count,
            "aux_count": len(aux),
            "digest": digest,
            "aux_sha256": _sha256_file(staging_aux),
            "created_unix": time.time(),
        }
        if recorded_shardings:
            # Optional key, same schema version: pre-elastic readers ignore
            # it, pre-elastic writers simply never produce it.
            manifest["shardings"] = recorded_shardings
        staging_manifest = os.path.join(staging, MANIFEST_NAME)
        with open(staging_manifest, "w") as fp:
            json.dump(manifest, fp, indent=2)
            fp.flush()
            os.fsync(fp.fileno())

    tracer.count("checkpoint_saves")
    tracer.add_span(
        "checkpoint/save", "checkpoint", start, time.perf_counter() - start,
        {"step": int(step), "rank": int(rank)},
    )
    if keep_last is not None and keep_last > 0:
        _gc_old_checkpoints(parent, keep_last)
    _gc_stale_staging(parent)
    for hook in list(_POST_SAVE_HOOKS):
        hook(ckpt_path)
    return ckpt_path


def load_checkpoint(ckpt_path: str, target: Optional[Any] = None) -> Dict[str, Any]:
    """Restore a checkpoint as a pytree of numpy leaves.

    Without `target`, Orbax returns generic containers (tuples/namedtuples
    come back as lists) — fine for counters and raw params. Pass a template
    pytree of the same structure (e.g. a freshly initialized train state) to
    restore exact container types, the moral equivalent of the reference's
    `load_state_dict` onto freshly-built modules.
    """
    import orbax.checkpoint as ocp

    ckpt_path = os.path.abspath(ckpt_path)
    # Manifest layout nests the Orbax tree under arrays/; pre-manifest
    # checkpoints stored it at the directory root.
    arrays_path = os.path.join(ckpt_path, "arrays")
    if not os.path.isdir(arrays_path):
        arrays_path = ckpt_path
    aux_file = os.path.join(ckpt_path, "aux.pkl")
    aux: Dict[str, Any] = {}
    if os.path.exists(aux_file):
        with open(aux_file, "rb") as fp:
            aux = pickle.load(fp)
    with ocp.StandardCheckpointer() as ckptr:
        if target is not None:
            template, _ = _split_state(
                jax.tree_util.tree_map(lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, target)
            )
            arrays = ckptr.restore(arrays_path, template)
        else:
            arrays = ckptr.restore(arrays_path)
    return _merge_state(arrays, aux)


def _keystr(path: Tuple[Any, ...]) -> str:
    """Normalize a tree_flatten_with_path key path to `a/b/0/c` form so dict
    keys, namedtuple fields, and list indices all print uniformly."""
    parts = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


# ------------------------------------------------- topology-elastic layout
def _record_shardings(state: Any) -> Dict[str, Any]:
    """Per-leaf ``NamedSharding`` descriptors for every device-backed leaf of
    ``state``, keyed by :func:`_keystr` path: the PartitionSpec entries (None
    / axis name / list of axis names per dim) plus the saving mesh's axis
    sizes. JSON-native so the descriptors live in the manifest."""
    from jax.sharding import NamedSharding

    recorded: Dict[str, Any] = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    for path, leaf in flat:
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            continue
        entries: List[Any] = []
        for entry in sharding.spec:
            if entry is None:
                entries.append(None)
            elif isinstance(entry, (tuple, list)):
                entries.append([str(a) for a in entry])
            else:
                entries.append(str(entry))
        recorded[_keystr(path)] = {
            "spec": entries,
            "mesh": {
                str(a): int(s)
                for a, s in zip(sharding.mesh.axis_names, sharding.mesh.devices.shape)
            },
        }
    return recorded


def load_recorded_shardings(ckpt_path: str) -> Optional[Dict[str, Any]]:
    """The manifest's recorded per-leaf shardings, or None for pre-elastic
    checkpoints (restores then fall back to the caller's static layout
    rule, e.g. ``shard_wide_params``)."""
    manifest = read_manifest(ckpt_path)
    if manifest is None:
        return None
    shardings = manifest.get("shardings")
    return shardings if isinstance(shardings, dict) and shardings else None


def _adapt_spec(spec_entries: List[Any], shape: Tuple[int, ...], mesh: Any) -> Any:
    """Replay a recorded PartitionSpec against a (possibly resized) mesh:
    each dim keeps its recorded axes only when they exist on the new mesh
    AND their combined size still divides the dim — otherwise that dim
    degrades to replicated. 8 -> 4 -> 1 devices all restore the same values;
    only the layout adapts."""
    from jax.sharding import PartitionSpec

    padded = list(spec_entries) + [None] * (len(shape) - len(spec_entries))
    out: List[Any] = []
    for dim, entry in zip(shape, padded):
        if entry is None:
            out.append(None)
            continue
        axes = [str(a) for a in (entry if isinstance(entry, (list, tuple)) else [entry])]
        axes = [a for a in axes if a in mesh.shape]
        size = 1
        for a in axes:
            size *= int(mesh.shape[a])
        if axes and dim % size == 0:
            out.append(tuple(axes) if len(axes) > 1 else axes[0])
        else:
            out.append(None)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def place_with_recorded_shardings(
    tree: Any,
    shardings: Dict[str, Any],
    mesh: Any,
    *,
    prefix: str = "",
    default: Optional[Callable[[Any], Any]] = None,
) -> Any:
    """Device-put a restored host pytree using the manifest's recorded
    per-leaf shardings, adapted to ``mesh`` (the resharding restore path).

    ``prefix`` maps this subtree into the checkpoint's key space (the state
    dict key it was saved under, e.g. ``"agent"``). Leaves without a record
    go through ``default`` (per-leaf callable) or replicate. Placement goes
    through ``core.mesh.put_sharded`` so the restore H2D lands on the
    transfer ledger like any other infeed.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from sheeprl_tpu.core import mesh as mesh_lib

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    placed = []
    for path, leaf in flat:
        rel = _keystr(path)
        key = f"{prefix}/{rel}" if prefix and rel else (prefix or rel)
        record = shardings.get(key)
        # Never np.asarray a device-backed leaf: on the CPU backend that is a
        # zero-copy VIEW of the live XLA buffer, and re-device_put of the view
        # aliases the original's memory — a later donation then frees a buffer
        # another live array still owns (heap corruption, not an exception).
        arr = leaf if hasattr(leaf, "shape") else np.asarray(leaf)
        if record is not None:
            spec = _adapt_spec(list(record.get("spec", [])), arr.shape, mesh)
            placed.append(mesh_lib.put_sharded(arr, NamedSharding(mesh, spec)))
        elif default is not None:
            placed.append(default(leaf))
        else:
            placed.append(mesh_lib.put_sharded(arr, NamedSharding(mesh, PartitionSpec())))
    return jax.tree_util.tree_unflatten(treedef, placed)


def restore_opt_state(fresh_opt_state: Any, ckpt_opt_state: Any) -> Any:
    """Pour restored optimizer leaves into a freshly-built optax state.

    Checkpoints store generic containers (namedtuples degrade on restore
    without a target); the authoritative structure comes from `tx.init`.
    Raises a readable error when the two trees disagree (e.g. the optimizer
    config changed between the run and the resume), naming the first few
    key-paths where the structures diverge.
    """
    import jax.numpy as jnp

    structure = jax.tree_util.tree_structure(fresh_opt_state)
    ckpt_tree = jax.tree_util.tree_map(jnp.asarray, ckpt_opt_state)
    leaves = jax.tree_util.tree_leaves(ckpt_tree)
    if structure.num_leaves != len(leaves):
        fresh_paths = [
            _keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(fresh_opt_state)[0]
        ]
        ckpt_paths = [
            _keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(ckpt_tree)[0]
        ]
        fresh_only = [p for p in fresh_paths if p not in set(ckpt_paths)][:4]
        ckpt_only = [p for p in ckpt_paths if p not in set(fresh_paths)][:4]
        detail = []
        if fresh_only:
            detail.append(f"expected by the fresh optimizer but missing from the checkpoint: {fresh_only}")
        if ckpt_only:
            detail.append(f"present in the checkpoint but not in the fresh optimizer: {ckpt_only}")
        if not detail:
            # Same path names, different multiplicity — show where the zip
            # first disagrees.
            for i, (a, b) in enumerate(zip(fresh_paths, ckpt_paths)):
                if a != b:
                    detail.append(f"first divergence at leaf {i}: fresh={a!r} vs checkpoint={b!r}")
                    break
        raise ValueError(
            f"Checkpointed optimizer state has {len(leaves)} leaves but the freshly-built "
            f"optimizer expects {structure.num_leaves} — did the optimizer config change since "
            f"the checkpoint? Diverging key-paths: " + ("; ".join(detail) if detail else "(none resolvable)")
        )
    return jax.tree_util.tree_unflatten(structure, leaves)


def _gc_old_checkpoints(ckpt_dir: str, keep_last: int) -> None:
    """Delete all but the newest `keep_last` checkpoints **per rank**,
    ordered by the policy-step embedded in the name (reference:
    callback.py:144-148). Grouping by rank matters: a global sort would let
    one rank's burst of saves GC another rank's only snapshot.

    Deletion is rename-first: the doomed checkpoint is atomically renamed to
    a ``.trash-*`` sibling before its contents are removed. A concurrent
    reader (``find_latest_valid_checkpoint`` in a resuming process, racing
    this writer's GC) therefore either sees the complete checkpoint or none
    at all — never a half-deleted one that passes structural validation but
    fails to load. A bare ``shutil.rmtree`` would expose exactly that torn
    window (manifest still readable, array files already gone)."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return
    by_rank: Dict[int, List[Tuple[int, str]]] = {}
    for name in names:
        m = _CKPT_RE.search(name)
        if m:
            by_rank.setdefault(int(m.group(2)), []).append(
                (int(m.group(1)), os.path.join(ckpt_dir, name))
            )
    for entries in by_rank.values():
        entries.sort()
        for _, path in entries[:-keep_last] if keep_last < len(entries) else []:
            trash = os.path.join(
                ckpt_dir, f"{_TRASH_PREFIX}{os.path.basename(path)}-{uuid.uuid4().hex[:8]}"
            )
            try:
                os.rename(path, trash)
            except OSError:
                continue  # another rank's GC got there first
            shutil.rmtree(trash, ignore_errors=True)
