"""Checkpoint write/read with keep-last garbage collection.

Parity with the reference CheckpointCallback (sheeprl/utils/callback.py:14-148):
state = model params + optimizer states + counters (+ algorithm extras such as
replay buffers), written at `<log_dir>/checkpoint/ckpt_<policy_step>_<rank>.ckpt`
with at most `keep_last` checkpoints retained.

Backend: Orbax `StandardCheckpointer` over a pure-numpy pytree — every jax
Array is pulled to host first so saves never hold device memory, and restores
return numpy leaves that algorithms re-shard themselves (the TPU equivalent of
torch's map_location). A checkpoint is a *directory* (Orbax layout), not a
single file; the `.ckpt` suffix is kept for reference-parity path printing.
Non-array leaves (ints, floats, strings, None) are pickled alongside in
`aux.pkl` because Orbax handles only array-like leaves.
"""

from __future__ import annotations

import os
import pickle
import re
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

_CKPT_RE = re.compile(r"ckpt_(\d+)_\d+\.ckpt$")

_ARRAY_TYPES = (np.ndarray, np.generic, jax.Array)


def _split_state(tree: Any, path: str = ""):
    """Split a pytree into (array-only tree with None placeholders, aux dict
    of path->non-array leaf)."""
    aux: Dict[str, Any] = {}

    def walk(node: Any, prefix: str) -> Any:
        if isinstance(node, dict):
            return {k: walk(v, f"{prefix}/{k}") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v, f"{prefix}/{i}") for i, v in enumerate(node)]
            if hasattr(node, "_fields"):  # namedtuple (optax states)
                return type(node)(*walked)
            return tuple(walked) if isinstance(node, tuple) else walked
        if isinstance(node, _ARRAY_TYPES):
            return np.asarray(node)
        aux[prefix] = node
        return None

    return walk(tree, path), aux


def _merge_state(tree: Any, aux: Dict[str, Any], path: str = "") -> Any:
    def walk(node: Any, prefix: str) -> Any:
        if isinstance(node, dict):
            return {k: walk(v, f"{prefix}/{k}") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v, f"{prefix}/{i}") for i, v in enumerate(node)]
            if hasattr(node, "_fields"):
                return type(node)(*walked)
            return tuple(walked) if isinstance(node, tuple) else walked
        if node is None and prefix in aux:
            return aux[prefix]
        return node

    return walk(tree, path)


def save_checkpoint(ckpt_path: str, state: Dict[str, Any], keep_last: Optional[int] = None) -> str:
    """Write `state` (pytree) to `ckpt_path` and GC old checkpoints in the
    same directory down to `keep_last` (reference: callback.py:30-38,144-148).
    """
    import orbax.checkpoint as ocp

    ckpt_path = os.path.abspath(ckpt_path)
    os.makedirs(os.path.dirname(ckpt_path), exist_ok=True)
    host_state = jax.tree_util.tree_map(lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, state)
    arrays, aux = _split_state(host_state)
    if os.path.exists(ckpt_path):
        shutil.rmtree(ckpt_path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt_path, arrays)
    with open(os.path.join(ckpt_path, "aux.pkl"), "wb") as fp:
        pickle.dump(aux, fp)
    if keep_last is not None and keep_last > 0:
        _gc_old_checkpoints(os.path.dirname(ckpt_path), keep_last)
    return ckpt_path


def load_checkpoint(ckpt_path: str, target: Optional[Any] = None) -> Dict[str, Any]:
    """Restore a checkpoint as a pytree of numpy leaves.

    Without `target`, Orbax returns generic containers (tuples/namedtuples
    come back as lists) — fine for counters and raw params. Pass a template
    pytree of the same structure (e.g. a freshly initialized train state) to
    restore exact container types, the moral equivalent of the reference's
    `load_state_dict` onto freshly-built modules.
    """
    import orbax.checkpoint as ocp

    ckpt_path = os.path.abspath(ckpt_path)
    aux_file = os.path.join(ckpt_path, "aux.pkl")
    aux: Dict[str, Any] = {}
    if os.path.exists(aux_file):
        with open(aux_file, "rb") as fp:
            aux = pickle.load(fp)
    with ocp.StandardCheckpointer() as ckptr:
        if target is not None:
            template, _ = _split_state(
                jax.tree_util.tree_map(lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, target)
            )
            arrays = ckptr.restore(ckpt_path, template)
        else:
            arrays = ckptr.restore(ckpt_path)
    return _merge_state(arrays, aux)


def restore_opt_state(fresh_opt_state: Any, ckpt_opt_state: Any) -> Any:
    """Pour restored optimizer leaves into a freshly-built optax state.

    Checkpoints store generic containers (namedtuples degrade on restore
    without a target); the authoritative structure comes from `tx.init`.
    Raises a readable error when the two trees disagree (e.g. the optimizer
    config changed between the run and the resume).
    """
    import jax.numpy as jnp

    structure = jax.tree_util.tree_structure(fresh_opt_state)
    leaves = jax.tree_util.tree_leaves(jax.tree_util.tree_map(jnp.asarray, ckpt_opt_state))
    if structure.num_leaves != len(leaves):
        raise ValueError(
            f"Checkpointed optimizer state has {len(leaves)} leaves but the freshly-built "
            f"optimizer expects {structure.num_leaves} — did the optimizer config change since the checkpoint?"
        )
    return jax.tree_util.tree_unflatten(structure, leaves)


def _gc_old_checkpoints(ckpt_dir: str, keep_last: int) -> None:
    """Delete all but the newest `keep_last` checkpoints, ordered by the
    policy-step embedded in the name (reference: callback.py:144-148)."""
    entries = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.search(name)
        if m:
            entries.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    entries.sort()
    for _, path in entries[:-keep_last] if keep_last < len(entries) else []:
        shutil.rmtree(path, ignore_errors=True)
