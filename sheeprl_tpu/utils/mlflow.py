"""MLflow integration: experiment logger + model registry manager
(reference: sheeprl/utils/mlflow.py:75-427).

Import-gated on the optional `mlflow` package. Differences from the
reference, by design:

- Models are jax/flax param pytrees, not torch modules, so they are logged
  as **pyfunc models** wrapping the flattened parameter arrays (saved with
  numpy .npz) instead of `mlflow.pytorch.log_model`.
- The reference ships a near-identical `log_models_from_checkpoint` per
  algorithm; here ONE generic `log_models_from_checkpoint` driven by the
  algorithm's `MODELS_TO_REGISTER` set covers every algorithm.
"""

from __future__ import annotations

import getpass
import os
import tempfile
from abc import ABC, abstractmethod
from datetime import datetime
from typing import Any, Dict, Literal, Optional, Sequence

import numpy as np

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE, require

require(_IS_MLFLOW_AVAILABLE, "mlflow", "mlflow")

import mlflow  # noqa: E402
from mlflow.entities.model_registry import ModelVersion  # noqa: E402
from mlflow.tracking import MlflowClient  # noqa: E402

VERSION_MD_TEMPLATE = "## **Version {}**\n"
DESCRIPTION_MD_TEMPLATE = "### Description: \n{}\n"


class MLflowLogger:
    """MLflow run logger exposing the log/log_dict/log_hyperparams surface
    the algorithms use (the analog of logger/mlflow.yaml's MLFlowLogger)."""

    def __init__(
        self,
        experiment_name: str,
        tracking_uri: Optional[str] = None,
        run_name: Optional[str] = None,
        tags: Optional[Dict[str, Any]] = None,
        **_: Any,
    ):
        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        mlflow.set_experiment(experiment_name)
        self._run = mlflow.start_run(run_name=run_name, tags=tags)
        self.run_id = self._run.info.run_id
        self.log_dir = None

    def log(self, name: str, value: Any, step: int) -> None:
        mlflow.log_metric(name.replace("/", "_"), float(np.asarray(value)), step=step)

    def log_dict(self, metrics: Dict[str, Any], step: int) -> None:
        mlflow.log_metrics(
            {k.replace("/", "_"): float(np.asarray(v)) for k, v in metrics.items()}, step=step
        )

    def log_hyperparams(self, cfg: Dict[str, Any]) -> None:
        flat: Dict[str, Any] = {}

        def _flatten(node, prefix=""):
            if isinstance(node, dict):
                for k, v in node.items():
                    _flatten(v, f"{prefix}{k}.")
            else:
                flat[prefix[:-1]] = node

        _flatten(dict(cfg))
        # MLflow caps params per batch; log defensively.
        import warnings as _warnings

        for i in range(0, len(flat), 90):
            chunk = dict(list(flat.items())[i : i + 90])
            try:
                mlflow.log_params({k: str(v)[:250] for k, v in chunk.items()})
            except Exception:  # pragma: no cover - server-side validation
                # One bad key must not discard the whole chunk: retry each
                # param alone and warn about the rejects.
                bad = []
                for k, v in chunk.items():
                    try:
                        mlflow.log_params({k: str(v)[:250]})
                    except Exception:
                        bad.append(k)
                if bad:
                    _warnings.warn(f"MLflow rejected hyperparameters: {bad}", UserWarning)

    def close(self) -> None:
        mlflow.end_run()


class _ParamsModel(mlflow.pyfunc.PythonModel):
    """Pyfunc wrapper over a saved flax param pytree (predict = identity over
    the flattened param listing; the artifact is the model of record)."""

    def load_context(self, context):
        self.params = dict(np.load(context.artifacts["params"], allow_pickle=False))

    def predict(self, context, model_input, params=None):  # pragma: no cover
        return {k: v.shape for k, v in self.params.items()}


def _flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_tree(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def log_model(tree: Any, artifact_path: str) -> Any:
    """Log one param pytree as an MLflow pyfunc model; returns ModelInfo."""
    with tempfile.TemporaryDirectory() as tmp:
        npz = os.path.join(tmp, "params.npz")
        np.savez(npz, **_flatten_tree(tree))
        return mlflow.pyfunc.log_model(
            artifact_path,
            python_model=_ParamsModel(),
            artifacts={"params": npz},
        )


def log_models_from_checkpoint(
    runtime, cfg: Dict[str, Any], state: Dict[str, Any], models_keys: Sequence[str]
) -> Dict[str, Any]:
    """Log every requested model's params from a checkpoint state under the
    current (or a new) MLflow run (reference: the per-algo
    log_models_from_checkpoint functions)."""
    model_info: Dict[str, Any] = {}
    run_cfg = cfg.get("run", {}) or {}
    exp_cfg = cfg.get("experiment", {}) or {}
    with mlflow.start_run(
        run_id=run_cfg.get("id"),
        experiment_id=exp_cfg.get("id"),
        run_name=run_cfg.get("name"),
        nested=True,
    ):
        for key in models_keys:
            if key not in state:
                continue
            model_info[key] = log_model(state[key], key)
        if cfg.get("to_log"):
            mlflow.log_dict(dict(cfg["to_log"]), "config.json")
    return model_info


def register_model_from_checkpoint(runtime, cfg: Dict[str, Any], state: Dict[str, Any], models_keys: Sequence[str]):
    """The registration CLI's worker: log the checkpoint's models and register
    the ones selected in cfg.model_manager.models (the reference's separate
    in-training register_model path collapses into this — registration always
    goes through a checkpoint here)."""
    model_info = log_models_from_checkpoint(runtime, cfg, state, models_keys)
    if cfg.model_manager.disabled:
        return
    tracking_uri = getattr(cfg, "tracking_uri", None) or os.environ.get("MLFLOW_TRACKING_URI")
    manager = MlflowModelManager(runtime, tracking_uri)
    for k, info in model_info.items():
        entry = cfg.model_manager.models.get(k)
        if entry is None:
            continue
        manager.register_model(info.model_uri, entry["model_name"], entry.get("description"), entry.get("tags"))


class AbstractModelManager(ABC):
    """Abstract model-registry manager (reference: mlflow.py:35-73)."""

    @abstractmethod
    def __init__(self, runtime) -> None:
        self.runtime = runtime

    @abstractmethod
    def register_model(
        self, model_location: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict] = None
    ) -> Any:
        """Register a model in the model registry."""

    @abstractmethod
    def get_latest_version(self, model_name: str) -> Any:
        """Get the latest version of a model."""

    @abstractmethod
    def transition_model(self, model_name: str, version: int, stage: str, description: Optional[str] = None) -> Any:
        """Transition the model with the given version to a new stage."""

    @abstractmethod
    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        """Delete a model with the given version."""

    @abstractmethod
    def register_best_models(
        self,
        experiment_name: str,
        models_info: Dict[str, Dict[str, Any]],
        metric: str = "Test/cumulative_reward",
        mode: Literal["max", "min"] = "max",
    ) -> Any:
        """Register the best models from an experiment."""

    @abstractmethod
    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        """Download the model with the given version."""


class MlflowModelManager(AbstractModelManager):
    """MLflow-backed registry manager (reference: mlflow.py:76-366)."""

    def __init__(self, runtime, tracking_uri: Optional[str] = None):
        super().__init__(runtime)
        self.tracking_uri = tracking_uri or mlflow.get_tracking_uri()
        mlflow.set_tracking_uri(self.tracking_uri)
        self.client = MlflowClient()

    @staticmethod
    def _author_and_date() -> str:
        return (
            f"**Author**: {getpass.getuser()}  \n"
            f"**Date**: {datetime.now().strftime('%d/%m/%Y %H:%M:%S')}  \n"
        )

    @staticmethod
    def _description(description: Optional[str]) -> str:
        return DESCRIPTION_MD_TEMPLATE.format(description or "-")

    def register_model(
        self, model_location: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict] = None
    ) -> ModelVersion:
        model_version = mlflow.register_model(model_uri=model_location, name=model_name, tags=tags)
        self.runtime.print(f"Registered model {model_name} with version {model_version.version}")
        registered = self.client.get_registered_model(model_name).description or ""
        header = "# MODEL CHANGELOG\n" if model_version.version == "1" else ""
        entry = (
            VERSION_MD_TEMPLATE.format(model_version.version)
            + self._author_and_date()
            + self._description(description)
        )
        self.client.update_registered_model(model_name, header + registered + entry)
        self.client.update_model_version(model_name, model_version.version, "# MODEL CHANGELOG\n" + entry)
        return model_version

    def get_latest_version(self, model_name: str) -> ModelVersion:
        versions = self.client.search_model_versions(f"name = '{model_name}'")
        latest = max(versions, key=lambda v: int(v.version))
        return latest

    def transition_model(
        self, model_name: str, version: int, stage: str, description: Optional[str] = None
    ) -> ModelVersion:
        model_version = self.client.get_model_version(model_name, version)
        self.runtime.print(f"Transitioning model {model_name} version {version} to {stage}")
        self.client.transition_model_version_stage(model_name, version, stage)
        entry = (
            f"### Transition: \n**Version {version}** to stage **{stage}**\n"
            + self._author_and_date()
            + self._description(description)
        )
        registered = self.client.get_registered_model(model_name).description or ""
        self.client.update_registered_model(model_name, registered + entry)
        self.client.update_model_version(
            model_name, version, (model_version.description or "") + entry
        )
        return self.client.get_model_version(model_name, version)

    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        self.runtime.print(f"Deleting model {model_name} version {version}")
        self.client.delete_model_version(model_name, version)
        registered = self.client.get_registered_model(model_name).description or ""
        entry = (
            f"### Deletion: \n**Version {version}**\n"
            + self._author_and_date()
            + self._description(description)
        )
        self.client.update_registered_model(model_name, registered + entry)

    def register_best_models(
        self,
        experiment_name: str,
        models_info: Dict[str, Dict[str, Any]],
        metric: str = "Test/cumulative_reward",
        mode: Literal["max", "min"] = "max",
    ) -> Dict[str, ModelVersion]:
        experiment = mlflow.get_experiment_by_name(experiment_name)
        if experiment is None:
            raise ValueError(f"Experiment '{experiment_name}' not found")
        order = "DESC" if mode == "max" else "ASC"
        runs = self.client.search_runs(
            [experiment.experiment_id],
            order_by=[f"metrics.`{metric.replace('/', '_')}` {order}"],
            max_results=1,
        )
        if not runs:
            raise ValueError(f"No runs found for experiment '{experiment_name}'")
        best_run = runs[0]
        registered: Dict[str, ModelVersion] = {}
        for key, info in models_info.items():
            # Reference contract (mlflow.py:276): the entry's registry name
            # is under "name"; "model_name" accepted as an alias.
            registered[key] = self.register_model(
                f"runs:/{best_run.info.run_id}/{info.get('path', key)}",
                info["name"] if "name" in info else info["model_name"],
                info.get("description"),
                info.get("tags"),
            )
        return registered

    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        if not os.path.exists(output_path):
            self.runtime.print(f"Creating output path {output_path}")
            os.makedirs(output_path)
        artifact_uri = self.client.get_model_version_download_uri(model_name, version)
        mlflow.artifacts.download_artifacts(artifact_uri=artifact_uri, dst_path=output_path)
