"""sheeprl_tpu.core.fleet — process-level supervision of actor replicas.

PR 8's EnvSupervisor restarts env *slices inside* the controller process;
this module promotes the same contract one level up, to the Podracer/Sebulba
actor fleet (arXiv:2104.06272): N actor-replica *processes* step environments
and ship rollout rows to the one learner process, which trains and broadcasts
params back. An actor death must read as a throughput dip, not a dead run.

Supervision contract (mirrors EnvSupervisor, plus the process-boundary
concerns that do not exist in-process):

- **Liveness** is a monotonic deadline fed by heartbeats piggybacked on every
  rollout shipment, with an idle-ping fallback for replicas that go long
  between shipments (PPO collecting a rollout segment, SAC waiting for first
  params). A SIGKILL'd replica is usually detected faster than the deadline:
  its pipe EOF surfaces on the very next poll.
- **Restart** of a dead replica uses exponential backoff with jitter and
  deterministic ``SeedSequence([seed, replica, restart])`` reseeding — the
  restarted process explores fresh trajectories instead of replaying the
  pre-crash ones, and a given (seed, replica, restart) triple is
  reproducible across runs.
- **Replay continuity**: transport is one private ``mp.Pipe`` pair per
  replica, so a replica killed mid-``send`` corrupts only its own stream.
  Complete-but-unread messages from a dead replica are drained WITHOUT
  ingestion and accounted on ``fleet/rows_dropped`` — rows either fully
  reach the replay buffer or are counted as lost, never half-ingested.
- **Quorum circuit breaker**: the learner keeps training as replicas
  permanently die (graceful degradation) until fewer than ``quorum`` can
  ever ship again, at which point :class:`FleetQuorumError` hard-errors the
  run — silent single-replica "fleets" are how throughput regressions hide.
- **Drain**: on learner preemption, :meth:`FleetSupervisor.drain_and_stop`
  delivers stop to every replica, waits for their byes, and only then does
  the learner commit its final (topology-elastic, see utils/checkpoint.py)
  sharded checkpoint and exit.

Every parent→child message (params broadcast AND stop) is serialized by the
replica's dedicated pump thread: two writers interleaving pickles on one
pipe is stream corruption, and a pump blocked on a hung replica dies with
the pipe instead of wedging the learner.

Observability: ``fleet/replicas_live``, ``fleet/replica_restarts``,
``fleet/heartbeat_age_s`` and ``fleet/rows_dropped`` live in the process
MetricsRegistry; spawn/restart/drain are tracer spans under the ``fleet``
category; every replica death is a flight-recorder trip recording who died,
why, and at which generation.
"""

from __future__ import annotations

import importlib
import multiprocessing as mp
import os
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from multiprocessing import connection as mp_connection
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.core import chaos

__all__ = [
    "FleetQuorumError",
    "FleetSupervisor",
    "ReplicaContext",
    "Shipment",
    "fleet_active",
    "replica_seed",
]

_LIVENESS_TICK_S = 0.1


class FleetQuorumError(RuntimeError):
    """Fewer replicas can ever ship again than ``fleet.quorum`` requires."""


def fleet_active(cfg: Any) -> bool:
    """True iff this run distributes acting over supervised replica
    processes. ``fleet.enabled`` null/absent means auto: active exactly when
    ``fleet.replicas > 1`` — the default single-replica config preserves
    today's in-process player loop bit for bit."""
    fleet_cfg = cfg.get("fleet") if hasattr(cfg, "get") else None
    if not fleet_cfg:
        return False
    enabled = fleet_cfg.get("enabled", None)
    replicas = int(fleet_cfg.get("replicas", 1) or 1)
    if enabled is None:
        return replicas > 1
    return bool(enabled)


def replica_seed(seed: int, replica: int, restart: int) -> int:
    """Deterministic per-(replica, restart) seed — same spawn-key derivation
    as EnvSupervisor.restart_seed, one level up: restart k of replica r is
    reproducible across runs yet never replays the pre-crash stream."""
    return int(np.random.SeedSequence([int(seed), int(replica), int(restart)]).generate_state(1)[0] % (2**31 - 1))


@dataclass
class Shipment:
    """One admitted actor→learner message, ready to ingest."""

    replica: int
    generation: int
    seq: int
    kind: str  # "rows" (per-step off-policy rows) | "rollout" (on-policy segment)
    rows: Dict[str, Any]
    env_steps: int
    episodes: List[Tuple[float, float]] = field(default_factory=list)
    meta: Dict[str, Any] = field(default_factory=dict)


# --------------------------------------------------------------- child side
class _StopRequested(Exception):
    """Raised inside a replica when the supervisor delivered stop mid-wait."""


class ReplicaContext:
    """The actor loop's handle on the fleet, inside the replica process.

    Owns the replica's half of both pipes, the per-replica chaos monkey
    (``kill9`` / ``drop_shipment`` specs targeting this replica index fire
    from :meth:`ship`), heartbeat bookkeeping, and the latest-params cache.
    """

    def __init__(
        self,
        cfg: Any,
        replica: int,
        restart: int,
        seed: int,
        log_dir: str,
        rows_conn: Any,
        ctrl_conn: Any,
        ping_interval_s: float,
        max_inflight: int = 0,
    ) -> None:
        self.cfg = cfg
        self.replica = int(replica)
        self.restart = int(restart)
        self.seed = int(seed)
        self.log_dir = log_dir
        self._rows_conn = rows_conn
        self._ctrl_conn = ctrl_conn
        self._ping_interval_s = float(ping_interval_s)
        # Credit-based backpressure (0 = unlimited): the supervisor returns
        # one credit per INGESTED shipment, so a replica can run at most
        # max_inflight shipments ahead of the learner — bounded pipe memory,
        # and on shared hardware the actor stops stealing cycles the train
        # step needs.
        self._max_inflight = int(max_inflight)
        self._credits = self._max_inflight
        self._seq = 0
        self._env_steps = 0
        self._last_sent = time.monotonic()
        self._stop = False
        self._params: Any = None
        self._version = 0
        self._monkey: Optional[chaos.ChaosMonkey] = None
        res = cfg.get("resilience") if hasattr(cfg, "get") else None
        chaos_cfg = res.get("chaos") if res else None
        if chaos_cfg and chaos_cfg.get("enabled", False) and self.restart == 0:
            # Generation 0 only: chaos's fired-injector registry is process
            # global, and a restarted replica is a NEW process — without this
            # gate a replica-scoped kill9 would re-fire every generation and
            # grind the slot into its max_restarts limit. One configured
            # fault is one fault (the chaos module's own contract).
            self._monkey = chaos.ChaosMonkey(chaos_cfg.get("injectors"), replica=self.replica)

    # ------------------------------------------------------------ shipping
    def ship(
        self,
        rows: Dict[str, Any],
        env_steps: int,
        episodes: Sequence[Tuple[float, float]] = (),
        kind: str = "rows",
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Ship rollout rows to the learner; the send doubles as this
        replica's heartbeat. False when a ``drop_shipment`` injector
        swallowed the message (the env steps still happened — the learner
        must survive the gap on idle pings)."""
        self._env_steps += int(env_steps)
        if self._monkey is not None:
            # kill9 lands here: mid-shipping, no drain — the realistic spot.
            self._monkey.on_step(self._env_steps)
        self._drain_ctrl()
        if chaos.maybe_drop("fleet.ship"):
            return False  # the credit is kept: nothing reached the wire
        if self._max_inflight > 0:
            while self._credits <= 0:
                if self._stop:
                    return False  # draining: don't queue rows nobody will read
                self.maybe_ping()  # liveness must not depend on throughput
                self._ctrl_conn.poll(0.05)
                self._drain_ctrl()
            self._credits -= 1
        self._send(kind, {
            "rows": rows,
            "env_steps": int(env_steps),
            "episodes": list(episodes),
            "meta": dict(meta or {}),
        })
        return True

    def _send(self, kind: str, payload: Any) -> None:
        self._seq += 1
        self._rows_conn.send((kind, self.restart, self._seq, payload))
        self._last_sent = time.monotonic()

    def maybe_ping(self) -> None:
        """Idle-ping fallback: call from any loop that can go longer than
        the ping interval without shipping (PPO's rollout collection, SAC's
        wait for first params) so liveness does not depend on throughput."""
        if time.monotonic() - self._last_sent >= self._ping_interval_s:
            self._send("ping", None)

    # -------------------------------------------------------------- params
    def _drain_ctrl(self) -> None:
        while self._ctrl_conn.poll(0):
            msg = self._ctrl_conn.recv()
            if msg[0] == "params":
                version = int(msg[1])
                if version > self._version:
                    self._version, self._params = version, msg[2]
            elif msg[0] == "credit":
                self._credits += int(msg[1])
            elif msg[0] == "stop":
                self._stop = True

    def poll_params(self) -> Optional[Tuple[int, Any]]:
        """Latest (version, host params) broadcast so far, or None."""
        self._drain_ctrl()
        return (self._version, self._params) if self._params is not None else None

    def wait_params(
        self, min_version: int = 1, timeout: Optional[float] = None, poll_s: float = 0.05
    ) -> Optional[Tuple[int, Any]]:
        """Block (with idle pings) until params of at least ``min_version``
        arrive; None on timeout or when the supervisor delivered stop
        mid-wait (callers check :meth:`should_stop` next)."""
        deadline = None if timeout is None else time.monotonic() + float(timeout)
        while True:
            self._drain_ctrl()
            if self._stop:
                return None
            if self._params is not None and self._version >= int(min_version):
                return self._version, self._params
            if deadline is not None and time.monotonic() >= deadline:
                return None
            self.maybe_ping()
            self._ctrl_conn.poll(poll_s)

    def should_stop(self) -> bool:
        self._drain_ctrl()
        return self._stop


def _resolve_actor(spec: str) -> Callable[[ReplicaContext], None]:
    """``"pkg.module:function"`` → callable. A dotted spec (not a pickled
    closure) is what makes the spawn start method viable: the child imports
    the module fresh, so the actor fn never drags the learner's state over."""
    module_name, _, fn_name = spec.partition(":")
    if not module_name or not fn_name:
        raise ValueError(f"actor spec must look like 'pkg.module:function', got {spec!r}")
    module = importlib.import_module(module_name)
    fn = getattr(module, fn_name, None)
    if not callable(fn):
        raise TypeError(f"actor spec {spec!r} does not name a callable")
    return fn


def _replica_entry(
    actor_spec: str,
    cfg: Any,
    log_dir: str,
    replica: int,
    restart: int,
    seed: int,
    ping_interval_s: float,
    max_inflight: int,
    sys_path: List[str],
    rows_conn: Any,
    ctrl_conn: Any,
) -> None:
    """Replica process main. Runs the actor loop until it returns (complete),
    the supervisor says stop, or something dies — always tries to tell the
    learner why via a final ``bye`` (a SIGKILL of course never reaches it;
    that is what pipe-EOF death evidence is for)."""
    import sys

    for entry in sys_path:  # spawn children must see the test/driver modules
        if entry not in sys.path:
            sys.path.insert(0, entry)
    ctx = ReplicaContext(
        cfg, replica, restart, seed, log_dir, rows_conn, ctrl_conn, ping_interval_s,
        max_inflight=max_inflight,
    )
    try:
        ctx._send("hello", {"pid": os.getpid()})
        actor = _resolve_actor(actor_spec)
        actor(ctx)
        ctx._send("bye", {"reason": "stop" if ctx.should_stop() else "complete"})
    except (BrokenPipeError, EOFError, OSError):
        # Learner side went away: nothing to report to, nobody to restart us.
        os._exit(1)
    except _StopRequested:
        try:
            ctx._send("bye", {"reason": "stop"})
        except Exception:  # noqa: BLE001
            pass
    except BaseException as exc:  # noqa: BLE001 - crash evidence beats silence
        traceback.print_exc()
        try:
            ctx._send("bye", {"reason": f"crash: {type(exc).__name__}: {exc}"})
        except Exception:  # noqa: BLE001
            pass
        os._exit(1)


# -------------------------------------------------------------- parent side
class _ParamPump(threading.Thread):
    """Single-writer serializer for one replica's control pipe.

    The learner thread never blocks on a slow/dead replica: it drops the
    newest params into the latest-wins slot and moves on; this daemon thread
    does the (potentially blocking) pickling+send and simply dies with the
    pipe when the replica does.
    """

    def __init__(self, conn: Any, name: str) -> None:
        super().__init__(name=name, daemon=True)
        self._conn = conn
        self._cond = threading.Condition()
        self._params: Optional[Tuple[int, Any]] = None  # graftlint: guarded-by(self._cond)
        self._credits = 0  # graftlint: guarded-by(self._cond)
        self._stop = False  # graftlint: guarded-by(self._cond)
        self._closed = False  # graftlint: guarded-by(self._cond)

    def offer_params(self, version: int, tree: Any) -> None:
        with self._cond:
            self._params = (int(version), tree)
            self._cond.notify()

    def grant(self, n: int = 1) -> None:
        """Flow-control credits: one per shipment the learner ingested."""
        with self._cond:
            self._credits += int(n)
            self._cond.notify()

    def offer_stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()

    def close(self) -> None:
        """Abandon without sending (the replica is already dead)."""
        with self._cond:
            self._closed = True
            self._cond.notify()

    def run(self) -> None:
        try:
            while True:
                with self._cond:
                    while not (
                        self._params is not None or self._credits or self._stop or self._closed
                    ):
                        self._cond.wait()
                    if self._closed:
                        return
                    params, self._params = self._params, None
                    credits, self._credits = self._credits, 0
                    stop = self._stop
                try:
                    if credits:
                        self._conn.send(("credit", credits, None))
                    if params is not None:
                        self._conn.send(("params", params[0], params[1]))
                    if stop:
                        self._conn.send(("stop", None, None))
                        return
                except (OSError, ValueError, BrokenPipeError):
                    return  # pipe died with the replica; supervisor handles it
        finally:
            try:
                self._conn.close()
            except OSError:
                pass


@dataclass
class _ReplicaSlot:
    index: int
    proc: Optional[Any] = None
    conn: Optional[Any] = None  # rows pipe, parent (read) end
    pump: Optional[_ParamPump] = None
    generation: int = 0  # == restart count of the live process
    restarts: int = 0  # total deaths observed
    last_seen: float = 0.0  # monotonic; fed by any message on the rows pipe
    dead: bool = False  # exhausted max_restarts — never coming back
    finished: bool = False  # actor loop returned cleanly — not a failure

    @property
    def live(self) -> bool:
        return self.proc is not None and not self.dead and not self.finished


class FleetSupervisor:
    """Runs and supervises N actor-replica processes feeding one learner.

    Lifecycle: ``start()`` → interleaved ``recv()`` / ``push_params()`` from
    the train loop → ``drain_and_stop()`` on preemption or ``close()``
    unconditionally. All methods are for the learner's main thread; the only
    internal thread is the per-replica param pump.
    """

    def __init__(
        self,
        actor_spec: str,
        cfg: Any,
        *,
        replicas: int,
        seed: int,
        log_dir: str = "",
        heartbeat_timeout_s: float = 30.0,
        ping_interval_s: float = 2.0,
        max_restarts: int = 8,
        backoff_base_s: float = 0.25,
        backoff_max_s: float = 5.0,
        backoff_jitter: float = 0.2,
        quorum: int = 1,
        start_method: str = "spawn",
        daemon_replicas: bool = True,
        drain_timeout_s: float = 10.0,
        max_inflight: int = 4,
    ) -> None:
        if replicas < 1:
            raise ValueError(f"fleet needs at least 1 replica, got {replicas}")
        if not (1 <= quorum <= replicas):
            raise ValueError(f"fleet.quorum must be in [1, replicas={replicas}], got {quorum}")
        self._actor_spec = actor_spec
        self._cfg = cfg
        self._replicas = int(replicas)
        self._seed = int(seed)
        self._log_dir = log_dir
        self._heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._ping_interval_s = float(ping_interval_s)
        self._max_restarts = int(max_restarts)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._backoff_jitter = float(backoff_jitter)
        self._quorum = int(quorum)
        self._daemon = bool(daemon_replicas)
        self._drain_timeout_s = float(drain_timeout_s)
        # Credit-based flow control: each replica may run at most max_inflight
        # shipments ahead of the learner's ingestion (0 = unbounded). Bounds
        # pipe memory AND stops replicas stealing CPU from the learner on
        # shared cores — the bench overhead gate depends on this.
        self._max_inflight = int(max_inflight)
        self._mp = mp.get_context(start_method)
        self._slots: List[_ReplicaSlot] = [_ReplicaSlot(index=i) for i in range(self._replicas)]
        self._pending: "deque[Shipment]" = deque()
        self._latest_params: Optional[Tuple[int, Any]] = None
        self._started = False
        self._stopped = False
        self._rows_dropped = 0
        self._restarts_total = 0

    @classmethod
    def from_config(cls, cfg: Any, actor_spec: str, *, seed: int, log_dir: str) -> "FleetSupervisor":
        f = cfg.fleet
        return cls(
            actor_spec,
            cfg,
            replicas=int(f.replicas),
            seed=int(seed),
            log_dir=log_dir,
            heartbeat_timeout_s=float(f.heartbeat_timeout_s),
            ping_interval_s=float(f.ping_interval_s),
            max_restarts=int(f.max_restarts),
            backoff_base_s=float(f.backoff_base_s),
            backoff_max_s=float(f.backoff_max_s),
            backoff_jitter=float(f.backoff_jitter),
            quorum=int(f.quorum),
            start_method=str(f.start_method),
            daemon_replicas=bool(f.daemon_replicas),
            drain_timeout_s=float(f.drain_timeout_s),
            max_inflight=int(f.max_inflight),
        )

    # ---------------------------------------------------------- observability
    def _registry(self):
        from sheeprl_tpu.telemetry.registry import default_registry

        return default_registry()

    def _tracer(self):
        from sheeprl_tpu.telemetry import tracer as tracer_mod

        return tracer_mod.current()

    def _publish_live(self) -> None:
        self._registry().gauge("fleet/replicas_live").set(float(self.live_replicas))

    def _publish_heartbeat_age(self, now: float) -> None:
        ages = [now - s.last_seen for s in self._slots if s.live and s.last_seen > 0.0]
        if ages:
            self._registry().gauge("fleet/heartbeat_age_s").set(max(0.0, max(ages)))

    # --------------------------------------------------------------- queries
    @property
    def replicas(self) -> int:
        return self._replicas

    @property
    def live_replicas(self) -> int:
        return sum(1 for s in self._slots if s.live)

    @property
    def restarts_total(self) -> int:
        return self._restarts_total

    @property
    def rows_dropped(self) -> int:
        return self._rows_dropped

    def replica_generation(self, index: int) -> int:
        return self._slots[index].generation

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        import sys

        if self._started:
            raise RuntimeError("FleetSupervisor.start() called twice")
        self._started = True
        self._sys_path = list(sys.path)
        start = time.perf_counter()
        for slot in self._slots:
            self._spawn(slot)
        self._publish_live()
        self._tracer().add_span(
            "fleet/spawn", "fleet", start, time.perf_counter() - start,
            {"replicas": self._replicas},
        )

    def _spawn(self, slot: _ReplicaSlot) -> None:
        """(Re)start one replica at its current generation."""
        # duplex=False pipes: (reader, writer). Rows flow child->parent, ctrl
        # flows parent->child — each process closes its copy of the far end
        # so a death reads as EOF instead of a forever-open pipe.
        rows_parent, rows_child = self._mp.Pipe(duplex=False)
        ctrl_child, ctrl_parent = self._mp.Pipe(duplex=False)
        seed = replica_seed(self._seed, slot.index, slot.generation)
        proc = self._mp.Process(
            target=_replica_entry,
            name=f"fleet-replica-{slot.index}-g{slot.generation}",
            args=(
                self._actor_spec,
                self._cfg,
                self._log_dir,
                slot.index,
                slot.generation,
                seed,
                self._ping_interval_s,
                self._max_inflight,
                self._sys_path,
                rows_child,
                ctrl_child,
            ),
            daemon=self._daemon,
        )
        proc.start()
        # The parent's copies of the child ends must close, or a dead child
        # never reads as EOF on the rows pipe.
        rows_child.close()
        ctrl_child.close()
        slot.proc = proc
        slot.conn = rows_parent
        slot.pump = _ParamPump(ctrl_parent, name=f"fleet-pump-{slot.index}-g{slot.generation}")
        slot.pump.start()
        slot.last_seen = time.monotonic()
        if self._latest_params is not None:
            # A restarted replica must not wait a full sync interval for
            # weights the learner already broadcast.
            slot.pump.offer_params(*self._latest_params)

    def push_params(self, params: Any, version: int) -> None:
        """Broadcast host params to every live replica (latest-wins per
        replica; a restarted replica is re-offered the newest broadcast)."""
        self._latest_params = (int(version), params)
        for slot in self._slots:
            if slot.live and slot.pump is not None:
                slot.pump.offer_params(int(version), params)

    # ------------------------------------------------------------------ recv
    def recv(self, timeout: Optional[float] = None) -> Optional[Shipment]:
        """Next admitted shipment, or None on timeout / fully-drained fleet.

        Liveness checks, restarts, heartbeat accounting, and quorum
        enforcement all run from inside this poll loop — the learner calling
        ``recv`` IS the supervisor's event loop; there is no hidden thread
        that could race the replay-buffer ingest.
        """
        if not self._started or self._stopped:
            return None
        deadline = None if timeout is None else time.monotonic() + float(timeout)
        while True:
            self._check_quorum()
            if self._pending:
                return self._hand_to_learner(self._pending.popleft())
            live = [s for s in self._slots if s.live and s.conn is not None]
            if not live:
                return None  # every replica finished or is permanently dead (>= quorum finished)
            now = time.monotonic()
            wait_s = _LIVENESS_TICK_S
            if deadline is not None:
                wait_s = min(wait_s, max(0.0, deadline - now))
            ready = mp_connection.wait([s.conn for s in live], wait_s)
            by_conn = {id(s.conn): s for s in live}
            # Pump EVERY ready conn one message before returning anything: a
            # replica that ships faster than the learner ingests keeps its
            # pipe permanently ready, and returning its rows first each time
            # would starve a dead sibling's EOF forever.
            for conn in ready:
                slot = by_conn[id(conn)]
                shipment = self._pump_conn(slot)
                if shipment is not None:
                    self._pending.append(shipment)
            self._liveness_pass(time.monotonic())
            self._publish_heartbeat_age(time.monotonic())
            if self._pending:
                return self._hand_to_learner(self._pending.popleft())
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def _hand_to_learner(self, shipment: Shipment) -> Shipment:
        """A shipment leaving the supervisor for ingestion refunds its flow
        credit — the sender may now run one shipment further ahead. Granting
        at hand-off (not at pipe read) keeps the credit bound honest: rows
        parked in ``_pending`` still count against the sender."""
        slot = self._slots[shipment.replica]
        if slot.live and slot.pump is not None:
            slot.pump.grant(1)
        return shipment

    def _pump_conn(self, slot: _ReplicaSlot) -> Optional[Shipment]:
        """Read one message from a ready rows pipe; death evidence (EOF,
        torn pickle) routes into the restart path."""
        try:
            msg = slot.conn.recv()
        except Exception as exc:  # noqa: BLE001 - EOF/torn msg == death evidence
            self._on_death(slot, f"rows pipe broke: {type(exc).__name__}")
            return None
        slot.last_seen = time.monotonic()
        kind, generation, seq, payload = msg
        if kind in ("hello", "ping"):
            return None
        if kind == "bye":
            reason = str((payload or {}).get("reason", "unknown"))
            if reason in ("stop", "complete"):
                self._on_finished(slot)
            else:
                self._on_death(slot, reason)
            return None
        # rows / rollout
        if int(generation) != slot.generation:
            # A pre-restart straggler: the replay-continuity contract says
            # drop-and-account, never half-ingest.
            self._account_dropped(int(payload.get("env_steps", 0)))
            return None
        self._tracer().count("fleet/shipments")
        return Shipment(
            replica=slot.index,
            generation=int(generation),
            seq=int(seq),
            kind=str(kind),
            rows=payload["rows"],
            env_steps=int(payload["env_steps"]),
            episodes=list(payload.get("episodes", [])),
            meta=dict(payload.get("meta", {})),
        )

    def _liveness_pass(self, now: float) -> None:
        for slot in self._slots:
            if not slot.live:
                continue
            if not slot.proc.is_alive():
                try:
                    pending = slot.conn is not None and slot.conn.poll(0)
                except OSError:
                    pending = False
                if pending:
                    # The process is gone but complete messages (possibly its
                    # clean bye) are still queued: read those first, or a
                    # cleanly-finished replica gets "restarted" by this race.
                    continue
                self._on_death(slot, f"process exited (code {slot.proc.exitcode})")
            elif now - slot.last_seen > self._heartbeat_timeout_s:
                # Hung, not dead: reap it ourselves, then restart. SIGKILL —
                # a process that stopped heartbeating cannot be trusted to
                # honor SIGTERM either.
                slot.proc.kill()
                slot.proc.join(timeout=5.0)
                self._on_death(slot, f"heartbeat timeout ({self._heartbeat_timeout_s:.1f}s)")

    # ------------------------------------------------------------ death path
    def _account_dropped(self, env_steps: int) -> None:
        if env_steps > 0:
            self._rows_dropped += int(env_steps)
            self._registry().counter("fleet/rows_dropped").inc(int(env_steps))

    def _drain_conn_dropping(self, slot: _ReplicaSlot) -> None:
        """Swallow whatever complete messages the dead replica managed to
        queue, accounting their rows as dropped — they were in flight when
        it died and the buffer never saw them."""
        try:
            while slot.conn.poll(0):
                msg = slot.conn.recv()
                if msg[0] in ("rows", "rollout"):
                    self._account_dropped(int(msg[3].get("env_steps", 0)))
        except Exception:  # noqa: BLE001 - the torn tail of the stream
            pass

    def _close_slot_transport(self, slot: _ReplicaSlot) -> None:
        if slot.pump is not None:
            slot.pump.close()
            slot.pump = None
        if slot.conn is not None:
            try:
                slot.conn.close()
            except OSError:
                pass
            slot.conn = None

    def _on_finished(self, slot: _ReplicaSlot) -> None:
        slot.finished = True
        if slot.proc is not None:
            slot.proc.join(timeout=5.0)
        self._close_slot_transport(slot)
        self._publish_live()

    def _on_death(self, slot: _ReplicaSlot, reason: str) -> None:
        start = time.perf_counter()
        self._drain_conn_dropping(slot)
        self._close_slot_transport(slot)
        if slot.proc is not None and slot.proc.is_alive():
            slot.proc.kill()
        if slot.proc is not None:
            slot.proc.join(timeout=5.0)
        slot.restarts += 1
        from sheeprl_tpu.telemetry import flight

        flight.dump_on_trip(
            "fleet/replica_death",
            message=f"replica {slot.index} (generation {slot.generation}) died: {reason}",
            args={
                "replica": slot.index,
                "generation": slot.generation,
                "restarts": slot.restarts,
                "reason": reason,
            },
        )
        if slot.restarts > self._max_restarts:
            slot.dead = True
            slot.proc = None
            self._publish_live()
            self._check_quorum()
            return
        # Exponential backoff with deterministic jitter: [seed, replica,
        # restart] keys the jitter stream too, so a flaky test cannot hide
        # behind restart timing.
        backoff = min(
            self._backoff_base_s * (2 ** max(0, slot.restarts - 1)), self._backoff_max_s
        )
        jitter_rng = np.random.default_rng(
            np.random.SeedSequence([self._seed, slot.index, slot.restarts, 0xB0FF])
        )
        time.sleep(backoff * (1.0 + self._backoff_jitter * float(jitter_rng.random())))
        slot.generation = slot.restarts
        self._spawn(slot)
        self._restarts_total += 1
        self._registry().counter("fleet/replica_restarts").inc()
        self._publish_live()
        self._tracer().add_span(
            "fleet/restart", "fleet", start, time.perf_counter() - start,
            {"replica": slot.index, "generation": slot.generation, "reason": reason},
        )

    def _check_quorum(self) -> None:
        can_ship = sum(1 for s in self._slots if not s.dead)
        if can_ship < self._quorum:
            raise FleetQuorumError(
                f"only {can_ship} of {self._replicas} replicas can still ship "
                f"(quorum {self._quorum}); refusing to limp along on a fleet "
                "that no longer exists"
            )

    # ------------------------------------------------------------------ stop
    def drain_and_stop(self, timeout: Optional[float] = None) -> None:
        """Coordinated whole-fleet drain: deliver stop, collect byes (rows
        arriving after the learner stopped ingesting are accounted dropped),
        reap everything. The caller commits its final checkpoint AFTER this
        returns — fleet first, save second, exit third."""
        if not self._started or self._stopped:
            return
        start = time.perf_counter()
        timeout = self._drain_timeout_s if timeout is None else float(timeout)
        # Shipments pumped off the wire but never handed to the learner are
        # dropped whole, same as rows still in flight.
        while self._pending:
            self._account_dropped(int(self._pending.popleft().env_steps))
        for slot in self._slots:
            if slot.live and slot.pump is not None:
                slot.pump.offer_stop()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pending = [s for s in self._slots if s.live and s.conn is not None]
            if not pending:
                break
            ready = mp_connection.wait(
                [s.conn for s in pending], min(0.25, max(0.0, deadline - time.monotonic()))
            )
            by_conn = {id(s.conn): s for s in pending}
            for conn in ready:
                slot = by_conn[id(conn)]
                try:
                    msg = slot.conn.recv()
                except Exception:  # noqa: BLE001 - died mid-drain; reap below
                    self._drain_conn_dropping(slot)
                    self._close_slot_transport(slot)
                    slot.finished = True
                    continue
                if msg[0] == "bye":
                    self._on_finished(slot)
                elif msg[0] in ("rows", "rollout"):
                    self._account_dropped(int(msg[3].get("env_steps", 0)))
        self._stop_all(graceful_joined=True)
        self._tracer().add_span(
            "fleet/drain", "fleet", start, time.perf_counter() - start,
            {"rows_dropped": self._rows_dropped},
        )

    def close(self) -> None:
        """Unconditional teardown (idempotent): terminate whatever still
        runs. Use :meth:`drain_and_stop` first when replay accounting and
        clean byes matter."""
        if not self._started or self._stopped:
            self._stopped = self._started or self._stopped
            return
        self._stop_all(graceful_joined=False)

    def _stop_all(self, graceful_joined: bool) -> None:
        for slot in self._slots:
            proc = slot.proc
            if proc is not None and proc.is_alive():
                if not graceful_joined:
                    proc.terminate()
                proc.join(timeout=5.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5.0)
            self._close_slot_transport(slot)
            slot.proc = None
        self._stopped = True
        self._publish_live()
