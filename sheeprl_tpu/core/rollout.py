"""Ship an on-policy rollout to the mesh for a fused bootstrap+GAE+update.

Shared by PPO and A2C (the two coupled on-policy loops): their whole
iteration is ONE jitted call — final-obs value bootstrap, GAE, epoch/
minibatch scans — so nothing round-trips the host between rollout and
update (reference shape: separate ``estimate_returns_and_advantages`` +
train loop, sheeprl/algos/ppo/ppo.py:345-420; here the fusion matters
because every extra dispatch pays the device-link latency).

Layout: every rollout tensor travels in ``(T, E, ...)`` — T the rollout
length, E the env columns — because the in-jit GAE scans T sequentially
while E is embarrassingly parallel. The env axis shards over `data`
whenever it divides the axis size; the minibatch phase reshards in-jit via
its ``with_sharding_constraint``. Multi-process coherence is the reason E
(not the flattened T*E) is the sharded axis: each process contributes ITS
env columns to the global array, so the GAE inputs and the sample rows a
column produces always come from the same process — a flattened row-block
assembly would interleave hosts differently for (T*E)-shaped and
(T, E)-shaped tensors and silently mix rollouts.

``share_data`` gathers along the env axis across hosts first (GAE is
independent per env column, so gather-then-GAE equals GAE-then-gather) —
the reference's every-process-trains-on-the-union mode (fabric.all_gather).
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Sequence, Tuple

import numpy as np

_SEQ_KEYS = ("rewards", "values", "dones")


def ship_rollout(
    runtime,
    local_data: Dict[str, Any],
    flat_keys: Sequence[str],
    next_obs_np: Dict[str, Any],
    share_data: bool = False,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Device trees ``(data, next_obs)`` for the fused train jit.

    ``data`` holds ``flat_keys`` + rewards/values/dones, all ``(T, E, ...)``
    (pixels stay uint8); ``next_obs`` is the final obs, one row per env.
    """
    import jax

    from sheeprl_tpu.telemetry import trace_context
    from sheeprl_tpu.telemetry.tracer import current as _current_tracer

    # The ship site is a cross-process seam (decoupled player -> trainer):
    # stamp the wire-format traceparent into the span args so the receiving
    # side of a future infeed transport can adopt the same trace.
    ctx = trace_context.current()
    args = {"traceparent": ctx.to_traceparent()} if ctx is not None else {}
    with _current_tracer().span("rollout/ship", "transfer", **args):
        return _ship_rollout(runtime, local_data, flat_keys, next_obs_np, share_data, jax)


def _ship_rollout(runtime, local_data, flat_keys, next_obs_np, share_data, jax):
    data = {k: np.asarray(local_data[k]) for k in (*flat_keys, *_SEQ_KEYS)}
    if share_data and jax.process_count() > 1:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(data)
        data = {
            k: np.moveaxis(v, 0, 1).reshape(v.shape[1], -1, *v.shape[3:])
            for k, v in gathered.items()
        }
        g_next = multihost_utils.process_allgather(next_obs_np)
        next_obs_np = jax.tree_util.tree_map(
            lambda v: v.reshape(-1, *v.shape[2:]), g_next
        )
    n_env_cols = data["rewards"].shape[1]
    if n_env_cols % runtime.world_size == 0:
        return (
            runtime.shard_batch(data, axis=1),
            runtime.shard_batch(next_obs_np, axis=0),
        )
    if jax.process_count() > 1 and not share_data:
        # Replication would be incoherent here: each process holds
        # DIFFERENT rollouts, and a "replicated" global array assumes every
        # copy is identical — GSPMD may then read any process's copy,
        # silently training on mixed data. No safe layout exists.
        raise ValueError(
            f"num_envs ({n_env_cols} env columns) must be divisible by the "
            f"data-axis size ({runtime.world_size}) in a multi-process run "
            "(or enable buffer.share_data to train on the gathered union)."
        )
    # Single process, OR the share_data allgather above already ran: every
    # process now holds the identical gathered union, so replication is
    # coherent (just pays the full copy per device).
    warnings.warn(
        f"num_envs ({n_env_cols}) is not divisible by the data-axis size "
        f"({runtime.world_size}): the rollout is replicated to every device "
        "(correct but pays a full copy per device). Set env.num_envs to a "
        "multiple of the device count for sharded transfers.",
        stacklevel=2,
    )
    return runtime.replicate(data), runtime.replicate(next_obs_np)


def fuse_gae_pool(
    agent,
    params,
    data: Dict[str, Any],
    next_obs: Dict[str, Any],
    flat_keys: Sequence[str],
    gamma: float,
    gae_lambda: float,
    include_values: bool = False,
) -> Dict[str, Any]:
    """The in-jit prologue both train steps share: bootstrap the final obs,
    GAE over ``(T, E, 1)`` scalars, and flatten everything into the
    ``(T*E, ...)`` minibatch pool (row order t*E + e)."""
    import jax.numpy as jnp

    from sheeprl_tpu.utils.ops import gae

    next_values = agent.get_values(params, next_obs)
    values = data["values"].astype(jnp.float32)
    returns, advantages = gae(
        data["rewards"].astype(jnp.float32),
        values,
        data["dones"].astype(jnp.float32),
        next_values,
        gamma,
        gae_lambda,
    )
    n = returns.shape[0] * returns.shape[1]
    pool = {k: data[k].reshape(n, *data[k].shape[2:]) for k in flat_keys}
    pool["returns"] = returns.reshape(n, *returns.shape[2:])
    pool["advantages"] = advantages.reshape(n, *advantages.shape[2:])
    if include_values:
        pool["values"] = values.reshape(n, *values.shape[2:])
    return pool
