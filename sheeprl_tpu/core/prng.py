"""PRNG threading utilities.

JAX's functional PRNG replaces the reference's global seeding
(fabric.seed_everything): one root key per run, split deterministically into
named streams; environment/numpy seeding stays host-side.
"""

from __future__ import annotations

import random
import warnings
from typing import Dict, Iterator, Optional, Sequence

import jax
import numpy as np


def seed_everything(seed: int, rank: Optional[int] = None) -> jax.Array:
    """Seed python/numpy host RNGs and return the root JAX key.

    The HOST streams (python/numpy — replay sampling, env glue) fold in the
    process ``rank`` so multi-host ranks draw distinct sequences; the
    returned JAX root key deliberately does NOT — model initialization must
    be identical on every rank (algorithms derive per-rank jax streams
    explicitly via fold_in where divergence is wanted).

    Callers that already know their rank (Runtime.seed_everything runs after
    launch(), when jax.process_index() is safe) pass it explicitly; with
    ``rank=None`` the rank is probed without initializing the backend.
    """
    if rank is None:
        # Never let this call INITIALIZE the backend: process_index() would
        # run plugin discovery (hanging on a wedged accelerator relay) and
        # then report rank 0 on every host anyway. If no backend exists yet,
        # use single-process semantics — multi-host flows seed via Runtime
        # AFTER launch(), when the real rank is known.
        rank = 0
        try:
            from jax._src import xla_bridge as _xb

            if _xb._backends:
                rank = jax.process_index()
        except Exception:
            # Private-API drift: falling back to rank 0 would correlate the
            # host streams (replay sampling) across every rank of a
            # multi-host run — say so instead of silently degrading.
            warnings.warn(
                "seed_everything could not detect the process rank "
                "(jax._src.xla_bridge drifted?); assuming rank 0. Multi-host "
                "callers should pass rank=jax.process_index() explicitly.",
                RuntimeWarning,
            )
    random.seed(seed + int(rank))
    np.random.seed(seed + int(rank))
    return jax.random.PRNGKey(seed)


def make_streams(root: jax.Array, names: Sequence[str]) -> Dict[str, jax.Array]:
    keys = jax.random.split(root, len(names))
    return {name: key for name, key in zip(names, keys)}


class KeySequence:
    """Host-side iterator of fresh PRNG keys (for per-iteration sampling).

    Only for host-loop use — never call inside jit (it would retrace).
    """

    def __init__(self, root: jax.Array):
        self._key = root

    def __iter__(self) -> Iterator[jax.Array]:
        return self

    def __next__(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def next(self) -> jax.Array:
        return self.__next__()
