"""Precision policies.

Keeps the reference's Fabric precision surface (``32-true``, ``bf16-mixed``,
``bf16-true``; configs/fabric/default.yaml) but maps it onto the JAX/TPU
model: parameters in fp32 unless bf16-true, compute (activations/matmuls) in
bf16 for both bf16 modes — bf16 is the MXU-native dtype. Reductions that the
reference keeps in fp32 (Moments quantiles, λ-returns, losses) stay fp32 in
the algorithms regardless of policy, matching its dtype-preserving LayerNorm
behavior (sheeprl/models/models.py:521-525).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp


@dataclass(frozen=True)
class Precision:
    name: str
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype

    @property
    def is_mixed(self) -> bool:
        return self.param_dtype != self.compute_dtype


_POLICIES = {
    "32-true": ("float32", "float32"),
    "bf16-mixed": ("float32", "bfloat16"),
    "bf16-true": ("bfloat16", "bfloat16"),
    # torch-style aliases accepted for config compatibility
    "16-mixed": ("float32", "bfloat16"),
    "32": ("float32", "float32"),
}


def resolve_precision(name: str) -> Precision:
    try:
        param, compute = _POLICIES[str(name)]
    except KeyError:
        raise ValueError(f"Unknown precision '{name}'. Valid: {sorted(_POLICIES)}") from None
    return Precision(str(name), jnp.dtype(param), jnp.dtype(compute))
