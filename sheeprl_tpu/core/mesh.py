"""Device mesh construction and sharding helpers.

This is the substrate layer that replaces Lightning Fabric's strategy system
(reference L0, SURVEY §1): instead of DDP process groups over NCCL/Gloo, a
single `jax.sharding.Mesh` spans every chip (ICI within a slice, DCN across
slices), and parallelism is expressed as sharding annotations that XLA lowers
to collectives.

Axes:
  - ``data``: batch (data-parallel) axis — replaces DDP gradient allreduce.
  - ``model``: optional tensor-parallel axis for wide layers (the reference
    has no TP at all; the 4096-wide RSSM stacks make it worthwhile on TPU).

Every put helper here accounts the bytes it moves into the live tracer
(``transfer/h2d_bytes``, ``transfer/d2d_bytes``, ``transfer/reshard_events``)
— the runtime complement of graftlint GL018's static resharding-thrash rule.
The counters ride the tracer's no-op fast path when telemetry is disabled,
so the accounting adds one function call per put to the infeed hot path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
# The canonical axis vocabulary, in mesh order. Everything that names an axis
# (PartitionSpecs, collectives, shard_map specs) must spell it through these
# constants — graftlint GL014 flags strays, and build_mesh refuses a mesh
# whose axis_names drift from this tuple.
AXIS_NAMES = (DATA_AXIS, MODEL_AXIS)


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_axis_size: Optional[int] = None,
    model_axis_size: int = 1,
) -> Mesh:
    """Build a 2-D (data, model) mesh over the given devices.

    ``data_axis_size=None`` uses all devices divided by ``model_axis_size``.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if model_axis_size < 1:
        raise ValueError(f"model_axis_size must be >= 1, got {model_axis_size}")
    if n % model_axis_size != 0:
        raise ValueError(f"model_axis_size {model_axis_size} does not divide device count {n}")
    if data_axis_size is None:
        data_axis_size = n // model_axis_size
    if data_axis_size * model_axis_size > n:
        raise ValueError(
            f"Requested mesh {data_axis_size}x{model_axis_size} exceeds available devices ({n})"
        )
    used = devices[: data_axis_size * model_axis_size]
    arr = np.asarray(used).reshape(data_axis_size, model_axis_size)
    mesh = Mesh(arr, AXIS_NAMES)
    assert tuple(mesh.axis_names) == AXIS_NAMES, (
        f"mesh axis names {mesh.axis_names} drifted from the canonical "
        f"vocabulary {AXIS_NAMES}; every sharding annotation in the repo "
        "spells axes through core.mesh constants"
    )
    return mesh


def split_player_trainer(mesh: Mesh, player_mode: str = "mesh", params: Any = None) -> tuple:
    """Partition a mesh's devices into (player device, trainer mesh).

    The substrate for decoupled player/trainer algorithms — the analog of the
    reference's rank-0 / optimization process-group split
    (sac_decoupled.py:563-584).

    ``player_mode`` is ``fabric.player_device`` (auto | host | mesh):

    - on-mesh (the classic split): device 0 plays, the rest train —
      requires at least 2 devices;
    - host (explicit, or auto over a high-latency link, core/player.py): the
      player runs on the host CPU backend and the trainer mesh keeps EVERY
      accelerator — decoupled training then works on a single chip, with no
      device sacrificed to latency-bound inference.

    Composes with tensor parallelism (``fabric.model_axis > 1``): the
    trainer partition keeps the ``model`` axis. With the player on the
    host the full (data x model) mesh trains; on-mesh, the player takes
    grid[0, 0] and the trainers keep data rows 1..d-1 as a
    (d-1) x model mesh — the rest of row 0 idles, exactly like the
    reference's rank-0 player process idles its accelerator share.

    ``params`` is the player-visible parameter tree (or None before it
    exists): ``auto`` refuses the host placement for actors above
    AUTO_MAX_PARAM_BYTES, whose packed post-update transfers would dominate.
    Callers that split before building the agent should re-split once the
    params exist.
    """
    from sheeprl_tpu.core.player import resolve_player_device

    model_size = int(mesh.shape[MODEL_AXIS])
    mesh_dev = mesh.devices.flat[0]
    player_mode = str(player_mode).lower()
    player = resolve_player_device(player_mode, mesh_dev, params=params)
    if player.platform == "cpu" and (player_mode == "host" or mesh_dev.platform != "cpu"):
        return player, mesh
    data_size = int(mesh.shape[DATA_AXIS])
    if data_size < 2:
        raise RuntimeError(
            "The decoupled on-mesh split needs at least 2 data rows (one player + at "
            "least one trainer row); run with fabric.devices>=2, or put the player on "
            "the host with fabric.player_device=host to train on every device."
        )
    grid = mesh.devices.reshape(data_size, model_size)
    if model_size > 1:
        # The cost of the on-mesh player placement must be visible, not just
        # documented: everything in row 0 except the player idles.
        import warnings

        warnings.warn(
            f"Decoupled on-mesh split with model_axis={model_size}: the player takes "
            f"grid[0,0] and the other {model_size - 1} device(s) of row 0 IDLE. "
            "Use fabric.player_device=host to train on every device instead.",
            UserWarning,
        )
    trainer_mesh = build_mesh(devices=list(grid[1:].flat), model_axis_size=model_size)
    return grid[0, 0], trainer_mesh


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch-leading array: leading dim split over `data`."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ------------------------------------------------------ transfer accounting
def _leaf_nbytes(x: Any) -> int:
    nbytes = getattr(x, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    try:
        return int(np.asarray(x).nbytes)
    except Exception:  # noqa: BLE001 - unsized leaf: account zero, not a crash
        return 0


def _account_transfer(kind: str, nbytes: int, calls: int = 1) -> None:
    """Count one put-helper invocation's bytes into the live tracer
    (``transfer/h2d_bytes`` etc. in telemetry.jsonl, mirrored onto /metrics
    by ``Telemetry.log_counters``). A disabled tracer makes this two cheap
    no-op calls — the infeed hot path keeps its budget."""
    from sheeprl_tpu.telemetry import tracer as tracer_mod

    t = tracer_mod.current()
    t.count(f"transfer/{kind}_bytes", float(nbytes))
    t.count(f"transfer/{kind}_calls", float(calls))


def shard_batch(tree: Any, mesh: Mesh, axis: int = 0) -> Any:
    """Device-put a host pytree with its ``axis`` dim sharded over `data`.

    This is the H2D infeed primitive: the analog of the reference's
    `to_tensor`/`get_tensor` bridge (sheeprl/data/buffers.py:1158-1180), but
    placing each shard directly on its device (no gather on one chip).
    """
    moved = 0
    data_size = int(mesh.shape[DATA_AXIS])

    def _put(x):
        nonlocal moved
        x = np.asarray(x)
        moved += x.nbytes
        spec = [None] * x.ndim
        if x.ndim > axis:
            n = int(x.shape[axis])
            if data_size > 1 and n % data_size != 0:
                lo = (n // data_size) * data_size
                hi = lo + data_size
                nearest = str(hi) if lo == 0 else f"{lo} or {hi}"
                raise ValueError(
                    f"shard_batch: batch dim {axis} of size {n} is not divisible by "
                    f"the `{DATA_AXIS}` mesh axis (size {data_size}); nearest valid "
                    f"batch size: {nearest}."
                )
            spec[axis] = DATA_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    out = jax.tree_util.tree_map(_put, tree)
    _account_transfer("h2d", moved)
    return out


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Device-put a host pytree fully replicated over the mesh (params)."""
    sharding = replicated_sharding(mesh)
    moved = 0

    def _put(x):
        nonlocal moved
        moved += _leaf_nbytes(x)
        return jax.device_put(x, sharding)

    out = jax.tree_util.tree_map(_put, tree)
    # Logical bytes: one host copy feeds the replicas (the fan-out across
    # devices is the runtime's broadcast, not a host read per replica).
    _account_transfer("h2d", moved)
    return out


def put_sharded(tree: Any, sharding: Any) -> Any:
    """Device-put a pytree onto an explicit sharding with the transfer
    ledger told the truth: host leaves count as H2D infeed, device-resident
    leaves whose layout differs count as D2D bytes plus one
    ``transfer/reshard_events`` tick per leaf — the runtime complement of
    graftlint GL018 (a loop that trips this every iteration is paying a
    resharding tax GL018 would flag statically), and leaves already laid
    out correctly count nothing (jax returns them as-is)."""
    h2d = d2d = reshards = 0

    def _put(x):
        nonlocal h2d, d2d, reshards
        current_sharding = getattr(x, "sharding", None)
        if current_sharding is None:
            h2d += _leaf_nbytes(x)
        elif current_sharding != sharding:
            d2d += _leaf_nbytes(x)
            reshards += 1
        return jax.device_put(x, sharding)

    out = jax.tree_util.tree_map(_put, tree)
    if h2d:
        _account_transfer("h2d", h2d)
    if d2d or reshards:
        _account_transfer("d2d", d2d, calls=reshards)
        from sheeprl_tpu.telemetry import tracer as tracer_mod

        tracer_mod.current().count("transfer/reshard_events", float(reshards))
    return out


def constrain(tree: Any, sharding: Any) -> Any:
    """Host-side ``with_sharding_constraint`` twin for already-device-backed
    trees: re-lay out every leaf onto ``sharding`` via :func:`put_sharded`
    (same accounting), for callers outside jit — inside jit, use
    ``jax.lax.with_sharding_constraint`` (host counters would only fire at
    trace time there, i.e. lie)."""
    return put_sharded(tree, sharding)


def param_partition_spec(x: Any, mesh: Mesh, min_dim: int = 1024) -> P:
    """The width-based model-parallel rule for a single param/opt leaf.

    Any floating-point leaf whose trailing dim is >= ``min_dim`` and divisible
    by the model-axis size is split along that dim over `model` (column-parallel
    for a dense kernel, matching split for its bias / optimizer moments);
    everything else replicates. Works on abstract leaves too — only ``shape``
    and ``dtype`` are consulted — so the same rule yields jit
    ``in_shardings``/``out_shardings`` and eager placements that agree.
    """
    model_size = int(mesh.shape[MODEL_AXIS])
    x = np.asarray(x) if not hasattr(x, "shape") else x
    ndim = getattr(x, "ndim", 0)
    wide = (
        model_size > 1
        and ndim >= 1
        and x.shape[-1] >= min_dim
        and x.shape[-1] % model_size == 0
        and jax.numpy.issubdtype(x.dtype, jax.numpy.floating)
    )
    if wide:
        return P(*([None] * (ndim - 1) + [MODEL_AXIS]))
    return P()


def param_partition_specs(tree: Any, mesh: Mesh, min_dim: int = 1024) -> Any:
    """Per-leaf :func:`param_partition_spec` over a whole pytree."""
    return jax.tree_util.tree_map(lambda x: param_partition_spec(x, mesh, min_dim), tree)


def param_shardings(tree: Any, mesh: Mesh, min_dim: int = 1024) -> Any:
    """Per-leaf ``NamedSharding`` tree under the wide-param rule — the form
    ``jax.jit(in_shardings=..., out_shardings=...)`` wants."""
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, param_partition_spec(x, mesh, min_dim)), tree
    )


def tree_shardings(tree: Any) -> Any:
    """Per-leaf committed shardings of an already-placed pytree (``None`` for
    host leaves, which jit treats as unconstrained). Feeding a train jit's
    ``in_shardings``/``out_shardings`` from the placed state guarantees the
    compiled layout matches the actual placement byte for byte."""
    return jax.tree_util.tree_map(lambda x: getattr(x, "sharding", None), tree)


def shard_wide_params(tree: Any, mesh: Mesh, min_dim: int = 1024) -> Any:
    """Place a param/opt pytree on the mesh with wide leaves sharded over the
    `model` axis (tensor parallelism) and everything else replicated.

    The rule is :func:`param_partition_spec` — width-based, not name-based.
    GSPMD propagates the layout through the jitted computation and inserts the
    all-gathers / reduce-scatters — the semantics are unchanged whatever the
    rule picks, only the layout varies. This is what makes
    `fabric.model_axis > 1` real for the 1024–4096-wide Dreamer dense stacks
    (SURVEY §2.1's TPU-native extra; the reference has no TP of any kind).
    """
    moved = 0

    def _put(x):
        nonlocal moved
        x = np.asarray(x) if not hasattr(x, "shape") else x
        moved += _leaf_nbytes(x)
        return jax.device_put(x, NamedSharding(mesh, param_partition_spec(x, mesh, min_dim)))

    out = jax.tree_util.tree_map(_put, tree)
    _account_transfer("h2d", moved)
    return out


@dataclasses.dataclass(frozen=True)
class PartitionPlan:
    """An algorithm's partition specs in one object: named batch layouts plus
    the wide-param rule, resolved against a concrete mesh.

    Each algo module exposes a ``partition_specs(mesh)`` hook returning one of
    these (the t5x axis-rules idea, scaled to this repo: layouts are data, not
    scattered ``NamedSharding`` constructions). Train-jit builders pull their
    batch/output shardings from the plan, and the runtime's
    ``shard_params`` placement agrees with :meth:`param_shardings` by
    construction, so explicit ``in_shardings`` never fight the placement.
    """

    mesh: Mesh
    batch_specs: Mapping[str, P] = dataclasses.field(
        default_factory=lambda: {"batch": P(DATA_AXIS)}
    )
    min_dim: int = 1024

    def spec(self, name: str = "batch") -> P:
        # Unregistered layouts resolve to replicated: a jit builder can ask
        # for a spec its algo never declared and get the safe default.
        return self.batch_specs.get(name, P())

    def sharding(self, name: str = "batch") -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(name))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def param_specs(self, tree: Any) -> Any:
        return param_partition_specs(tree, self.mesh, self.min_dim)

    def param_shardings(self, tree: Any) -> Any:
        return param_shardings(tree, self.mesh, self.min_dim)

    def place_params(self, tree: Any) -> Any:
        return shard_wide_params(tree, self.mesh, self.min_dim)

    @property
    def data_size(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])


def default_partition_plan(
    mesh: Mesh,
    batch_specs: Optional[Mapping[str, P]] = None,
    min_dim: int = 1024,
) -> PartitionPlan:
    """Data-sharded batch + wide-param model sharding — the default every
    ``partition_specs()`` hook starts from."""
    specs: Dict[str, P] = {"batch": P(DATA_AXIS)}
    if batch_specs:
        specs.update(batch_specs)
    return PartitionPlan(mesh=mesh, batch_specs=specs, min_dim=min_dim)


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    data = mesh.shape[DATA_AXIS]
    if global_batch % data != 0:
        raise ValueError(f"Global batch {global_batch} not divisible by data axis {data}")
    return global_batch // data
