"""The Anakin lane: rollout AND training fused into single-jit supersteps.

Podracer/Anakin (arXiv:2104.06272) observes that when the environment is a
pure-JAX transform (sheeprl_tpu/envs/jax/), the entire RL loop — vmapped env
batch, policy forward, autoreset, trajectory accumulation, gradient steps —
compiles into ONE XLA program. The host's only job per *superstep* (T env
steps × E envs + the attached training work) is dispatching one or two jits
and threading counters; interaction cost disappears into the schedule and
`core/interact.py` is bypassed entirely.

Enabled per run with ``env.jax_native=true`` + ``algo.fused_rollout=true``
(see :func:`fused_enabled`; the Gymnasium lane is untouched otherwise).
Three drivers, mirroring their host-interaction mains step for step:

- :func:`ppo_fused_main`: one donated jit per iteration = T-step rollout
  scan (SAME_STEP in-scan autoreset + per-step truncation bootstrap) feeding
  the shared ``fuse_gae_pool`` prologue and the epochs×minibatches update
  scans (algos/ppo/ppo.py:make_update_pool). 1 dispatch per superstep.
- :func:`sac_fused_main`: a T-step rollout jit writing transitions straight
  into the device replay ring (data/device_buffer.py:make_step_write_fn),
  then the existing ring-sampled K-step fused train jit. 2 dispatches.
- :func:`dreamer_v3_fused_main`: rollout scan threading the recurrent
  player latents (masked in-scan reset) with the dreamer row convention
  (main row + sparse episode-boundary reset rows), then the fused
  sequence-model train jit. 2 dispatches.

Counters, telemetry (per-superstep tracer span, StepTimer's coalesced
metrics fetch, in-jit health probes), resilience (iteration-boundary
preemption drain, health-gated checkpoints) and checkpoint layouts are kept
identical to the host lane, so fused-lane checkpoints resume on the
Gymnasium lane and vice versa.

Caveats (howto/anakin_lane.md): episode stats surface once per log interval
(one coalesced transfer) instead of per step; SAC/dreamer supersteps cover
``algo.fused_superstep_steps`` host-lane iterations, so replay-ratio and
target-EMA cadences are reproduced at superstep granularity (within one
superstep of the host lane's schedule).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, List, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.core.mesh import DATA_AXIS, MODEL_AXIS
from sheeprl_tpu.core.resilience import watch
from sheeprl_tpu.core.rollout import fuse_gae_pool
from sheeprl_tpu.data.device_buffer import DeviceReplayRing
from sheeprl_tpu.envs.jax import JaxEnv, action_to_env, canonical_action_space, make_jax_env
from sheeprl_tpu.telemetry import tracer as tracer_mod
from sheeprl_tpu.utils.checkpoint import load_checkpoint, restore_opt_state, save_checkpoint
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, polynomial_decay, save_configs

__all__ = [
    "fused_enabled",
    "last_run_stats",
    "ppo_fused_main",
    "sac_fused_main",
    "dreamer_v3_fused_main",
]


def fused_enabled(cfg) -> bool:
    """True when this run opted into the Anakin lane."""
    return bool(cfg.env.get("jax_native", False)) and bool(cfg.algo.get("fused_rollout", False))


# Dispatch accounting for the bench's head-to-head legs: supersteps run,
# jit dispatches issued, env steps covered (scripts/bench.py reads these).
_RUN_STATS: Dict[str, int] = {"supersteps": 0, "jit_dispatches": 0, "env_steps": 0}


def last_run_stats() -> Dict[str, int]:
    """Counters from the most recent fused run (bench reporting)."""
    return dict(_RUN_STATS)


def _reset_run_stats() -> None:
    _RUN_STATS.update(supersteps=0, jit_dispatches=0, env_steps=0)


# --------------------------------------------------------------- shared bits
def _shard_superstep_enabled(cfg, mesh, num_envs: int) -> bool:
    """True when the fused supersteps run SPMD via shard_map over ``data``.

    The sharded program is the SAME program on every topology — a data axis of
    size 1 still goes through shard_map (an identity partitioning), and every
    per-env PRNG stream is keyed by the env's GLOBAL id — so enabling more
    shards never changes the math, only where each env's rows live."""
    if not bool(cfg.fabric.get("shard_superstep", True)):
        return False
    if int(mesh.shape[MODEL_AXIS]) > 1:
        # Params enter the superstep replicated (in_spec P()); a model-sharded
        # tree would be all-gathered every dispatch. Keep GSPMD placement.
        return False
    data_size = int(mesh.shape[DATA_AXIS])
    if num_envs % data_size != 0:
        warnings.warn(
            f"fabric.shard_superstep: env.num_envs={num_envs} is not divisible by the "
            f"`{DATA_AXIS}` mesh axis (size {data_size}); the superstep stays replicated."
        )
        return False
    return True


def _fold_env_keys(key: jax.Array, genv: jax.Array) -> jax.Array:
    """One PRNG key per env, derived from the env's GLOBAL id (GL017): the
    stream an env sees is invariant to how envs are split across shards."""
    return jax.vmap(jax.random.fold_in, (None, 0))(key, genv)


def _global_env_ids(e_local: int, sharded: bool) -> jax.Array:
    """Global env ids for this shard's ``e_local`` rows. Under shard_map the
    axis index recovers the shard's offset; unsharded it's just arange."""
    if sharded:
        return jax.lax.axis_index(DATA_AXIS) * e_local + jnp.arange(e_local)
    return jnp.arange(e_local)


def _where_done(done: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """Per-env select on the done mask, broadcasting over feature dims."""
    return jnp.where(done.reshape(done.shape + (1,) * (a.ndim - 1)), a, b)


def _resolve_env(cfg) -> JaxEnv:
    env = make_jax_env(cfg.env.id)
    limit = cfg.env.get("max_episode_steps", None)
    if limit is not None:
        env.max_episode_steps = int(limit)
    return env


def _single_obs_key(cfg, env: JaxEnv) -> Tuple[str, bool]:
    """The dict key make_env would file this obs under (pixel vs vector), so
    fused-lane agents get byte-identical param trees to Gymnasium-lane ones."""
    pixel = len(env.observation_space.shape) >= 2
    keys = list(cfg.algo.cnn_keys.encoder if pixel else cfg.algo.mlp_keys.encoder)
    other = list(cfg.algo.mlp_keys.encoder if pixel else cfg.algo.cnn_keys.encoder)
    if len(keys) != 1 or other:
        raise ValueError(
            "The fused lane supports exactly one encoder key matching the env's observation "
            f"kind; got cnn={list(cfg.algo.cnn_keys.encoder)} mlp={list(cfg.algo.mlp_keys.encoder)} "
            f"for an observation of shape {env.observation_space.shape}"
        )
    return keys[0], pixel


def _env_actions(real_actions: jax.Array, env: JaxEnv, to_env, is_continuous: bool, num_envs: int):
    shape = env.action_space.shape
    actions = real_actions.reshape((num_envs, *shape)) if shape else real_actions.reshape((num_envs,))
    if is_continuous:
        return to_env(actions)
    return actions.astype(jnp.int32)


def _fetch_row_counts(rows_written: jax.Array) -> np.ndarray:
    """ONE coalesced device->host transfer per superstep: the [E] per-env
    written-row counts (dreamer's sparse reset rows make ring occupancy
    data-dependent, and the host mirror must track it for ready())."""
    return np.asarray(jax.device_get(rows_written), dtype=np.int64)


def _drain_episode_stats(pending: List[Dict[str, Any]]) -> List[Tuple[int, float, float]]:
    """(env_idx, return, length) for every episode that ended in the interval.
    ONE coalesced device->host transfer for all queued supersteps."""
    if not pending:
        return []
    fetched = jax.device_get(pending)
    episodes: List[Tuple[int, float, float]] = []
    for ep in fetched:
        done = np.asarray(ep["done"])
        for t, e in zip(*np.nonzero(done)):
            episodes.append((int(e), float(ep["returns"][t, e]), float(ep["lengths"][t, e])))
    return episodes


def _log_episode_stats(pending, aggregator, runtime, policy_step, log_level) -> None:
    if log_level <= 0:
        pending.clear()
        return
    for env_i, ep_rew, ep_len in _drain_episode_stats(pending):
        if aggregator and not aggregator.disabled:
            if "Rewards/rew_avg" in aggregator:
                aggregator.update("Rewards/rew_avg", ep_rew)
            if "Game/ep_len_avg" in aggregator:
                aggregator.update("Game/ep_len_avg", ep_len)
        runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{env_i}={ep_rew}")
    pending.clear()


def _superstep_taus(iter_start: int, iter_end: int, freq_iters: int, tau: float, k: int) -> np.ndarray:
    """Spread the host lane's per-iteration EMA cadence over a K-step fused
    train scan: one ``tau`` entry per EMA-eligible iteration in
    ``(iter_start, iter_end]``, evenly placed (SAC's iteration-based cadence
    reproduced at superstep granularity)."""
    taus = np.zeros(max(k, 1), np.float32)
    if k <= 0 or freq_iters <= 0:
        return taus
    n_ema = sum(1 for i in range(iter_start + 1, iter_end + 1) if i % freq_iters == 0)
    if n_ema == 0:
        return taus
    for idx in np.unique(np.linspace(0, k - 1, num=min(n_ema, k)).round().astype(int)):
        taus[idx] = tau
    return taus


# ----------------------------------------------------------------------- PPO
def ppo_fused_main(runtime, cfg: Dict[str, Any]):
    from sheeprl_tpu.algos.ppo.agent import actions_metadata, build_agent
    from sheeprl_tpu.algos.ppo.ppo import _current_lr, make_optimizer, make_update_pool
    from sheeprl_tpu.algos.ppo.utils import test

    _reset_run_stats()
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)
    mesh = runtime.mesh
    rank = runtime.global_rank
    world_size = jax.process_count()

    state = None
    if cfg.checkpoint.resume_from:
        state = load_checkpoint(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir} (fused Anakin lane)")
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    watchdog = runtime.resilience.watchdog
    health = runtime.health

    # ------------------------------------------------------------------ env
    env = _resolve_env(cfg)
    num_envs = int(cfg.env.num_envs)
    obs_key, _pixel = _single_obs_key(cfg, env)
    observation_space = gym.spaces.Dict({obs_key: env.observation_space})
    action_space = canonical_action_space(env)
    actions_dim, is_continuous = actions_metadata(action_space)
    to_env = action_to_env(env)
    clip_rewards = bool(cfg.env.clip_rewards)

    # ---------------------------------------------------------------- agent
    with runtime.host_init():
        agent, params = build_agent(
            runtime, actions_dim, is_continuous, cfg, observation_space,
            state["agent"] if state is not None else None,
        )
        tx, base_lr = make_optimizer(cfg)
        opt_state = tx.init(params)
        if state is not None:
            opt_state = restore_opt_state(opt_state, state["optimizer"])
    params = runtime.shard_params(params)
    opt_state = runtime.shard_params(opt_state)

    # Arm per-shard goodput accounting: the observatory needs the mesh and the
    # realised param layouts to attribute MFU/imbalance per data-shard.
    telemetry.set_mesh(mesh)
    telemetry.record_param_layouts(params)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    # ------------------------------------------------------------- counters
    last_train = 0
    train_step_count = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(cfg.env.num_envs * cfg.algo.rollout_steps * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

    T = int(cfg.algo.rollout_steps)
    E = num_envs
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)
    flat_keys = (obs_key, "actions", "logprobs")

    # ------------------------------------------------------------ superstep
    update_pool = make_update_pool(agent, tx, cfg, mesh)
    step_v = jax.vmap(env.step)
    reset_v = jax.vmap(env.reset)
    use_shard = _shard_superstep_enabled(cfg, mesh, num_envs)

    def rollout_core(params, env_state, obs, ep_ret, ep_len, k_roll):
        # Local (per-shard) env width: under shard_map each shard traces with
        # its E/data rows; unsharded this is just E. Every per-env PRNG stream
        # is keyed by the env's GLOBAL id so both trace to the same streams.
        e_local = obs.shape[0]
        genv = _global_env_ids(e_local, use_shard)

        def body(carry, step_key):
            env_state, obs, ep_ret, ep_len = carry
            k_policy, k_step, k_reset = jax.random.split(step_key, 3)

            def _policy(o, k):
                a_cat, a_real, lp, v, _next_k = agent.player_step(params, {obs_key: o[None]}, k)
                return a_cat[0], a_real[0], lp[0], v[0]

            # Per-env singleton-batch policy step: action sampling consumes
            # the env's own key, so the draw is independent of batch width
            # (the deterministic forward is row-independent either way).
            actions_cat, real_actions, logprobs, values = jax.vmap(_policy)(
                obs, _fold_env_keys(k_policy, genv)
            )
            new_state, new_obs, reward, done, info = step_v(
                env_state, _env_actions(real_actions, env, to_env, is_continuous, e_local),
                _fold_env_keys(k_step, genv),
            )
            # Truncation bootstrap on the TRUE next obs (pre-reset), exactly
            # the host lane's final_obs path; raw rewards feed episode stats.
            boot = agent.get_values(params, {obs_key: new_obs})[:, 0]
            buf_reward = reward + gamma * boot * info["truncated"].astype(jnp.float32)
            if clip_rewards:
                buf_reward = jnp.tanh(buf_reward)
            ep_ret = ep_ret + reward
            ep_len = ep_len + 1
            # SAME_STEP autoreset: done envs restart immediately; the stored
            # transition keeps the pre-reset obs/reward.
            r_state, r_obs = reset_v(_fold_env_keys(k_reset, genv))
            env_state = jax.tree_util.tree_map(
                lambda r, n: _where_done(done, r, n), r_state, new_state
            )
            obs_next = _where_done(done, r_obs, new_obs)
            traj = {
                obs_key: obs,
                "actions": actions_cat.astype(jnp.float32),
                "logprobs": logprobs,
                "values": values,
                "rewards": buf_reward[:, None],
                "dones": done.astype(jnp.float32)[:, None],
            }
            ep_info = {"done": done, "returns": ep_ret, "lengths": ep_len.astype(jnp.float32)}
            ep_ret = jnp.where(done, 0.0, ep_ret)
            ep_len = jnp.where(done, 0, ep_len)
            return (env_state, obs_next, ep_ret, ep_len), (traj, ep_info)

        (env_state, obs, ep_ret, ep_len), (traj, ep_info) = jax.lax.scan(
            body, (env_state, obs, ep_ret, ep_len), jax.random.split(k_roll, T)
        )
        return env_state, obs, ep_ret, ep_len, traj, ep_info

    rollout_fn = rollout_core
    if use_shard:
        # SPMD rollout: each shard steps its own envs and accumulates its own
        # [T, E/data] trajectory columns; GAE + the update pool downstream
        # stay GSPMD over the `data`-sharded pool. check_rep=False because
        # the unmentioned `model` axis (size 1 here) defeats replication
        # inference; params and keys arrive replicated by construction.
        p_env = P(DATA_AXIS)
        p_traj = P(None, DATA_AXIS)
        rollout_fn = shard_map(
            rollout_core,
            mesh=mesh,
            in_specs=(P(), p_env, p_env, p_env, p_env, P()),
            out_specs=(p_env, p_env, p_env, p_env, p_traj, p_traj),
            check_rep=False,
        )

    def rollout_and_train(params, opt_state, env_state, obs, ep_ret, ep_len, key, clip_coef, ent_coef):
        next_key, k_roll, k_train = jax.random.split(key, 3)
        env_state, obs, ep_ret, ep_len, traj, ep_info = rollout_fn(
            params, env_state, obs, ep_ret, ep_len, k_roll
        )
        pool = fuse_gae_pool(
            agent, params, traj, {obs_key: obs}, flat_keys, gamma, gae_lambda, include_values=True
        )
        params, opt_state, metrics, _unused_key = update_pool(
            params, opt_state, pool, k_train, clip_coef, ent_coef
        )
        return params, opt_state, env_state, obs, ep_ret, ep_len, ep_info, metrics, next_key

    superstep = jax.jit(rollout_and_train, donate_argnums=(0, 1, 2, 3, 4, 5))

    init_key, loop_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    # Env init is computed from GLOBAL per-env keys (identical on every
    # topology), then the carries land on their `data`-axis shards.
    env_state, obs = jax.jit(reset_v)(jax.random.split(init_key, E))
    ep_ret = jnp.zeros((E,), jnp.float32)
    ep_len = jnp.zeros((E,), jnp.int32)
    if use_shard:
        env_sharding = NamedSharding(mesh, P(DATA_AXIS))
        env_state, obs, ep_ret, ep_len = jax.device_put(
            (env_state, obs, ep_ret, ep_len), env_sharding
        )

    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    perf = telemetry.perf
    keep_train_metrics = (aggregator is not None and not aggregator.disabled) or health.enabled
    pending_eps: List[Dict[str, Any]] = []
    tracer = tracer_mod.current()

    for iter_num in range(start_iter, total_iters + 1):
        telemetry.advance(policy_step)
        guard.advance(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/train_time"):
            clip_arr = np.asarray(cfg.algo.clip_coef, np.float32)
            ent_arr = np.asarray(cfg.algo.ent_coef, np.float32)
            # Goodput accounting BEFORE the dispatch (the superstep donates
            # its carry): the whole rollout+train program is one key.
            perf.note(
                "rollout/superstep", superstep,
                (params, opt_state, env_state, obs, ep_ret, ep_len, loop_key, clip_arr, ent_arr),
                steps=1,
            )
            with tracer.span("fused/superstep", "train"), train_timer.step(), watch(
                watchdog, "train_dispatch"
            ):
                (
                    params, opt_state, env_state, obs, ep_ret, ep_len, ep_info, train_metrics, loop_key,
                ) = superstep(
                    params, opt_state, env_state, obs, ep_ret, ep_len, loop_key,
                    clip_arr, ent_arr,
                )
            train_timer.pend(params, train_metrics if keep_train_metrics else None)
        pending_eps.append(ep_info)
        train_step_count += world_size
        _RUN_STATS["supersteps"] += 1
        _RUN_STATS["jit_dispatches"] += 1
        _RUN_STATS["env_steps"] += T * E

        # ----------------------------------------------------------- logging
        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        )
        if should_log:
            fetched_train_metrics = train_timer.flush()
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            _log_episode_stats(pending_eps, aggregator, runtime, policy_step, cfg.metric.log_level)
            if aggregator and not aggregator.disabled:
                for tm in fetched_train_metrics:
                    aggregator.update("Loss/policy_loss", tm["policy_loss"])
                    aggregator.update("Loss/value_loss", tm["value_loss"])
                    aggregator.update("Loss/entropy_loss", tm["entropy_loss"])
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if cfg.metric.log_level > 0 and logger is not None:
            logger.log("Info/learning_rate", _current_lr(opt_state, base_lr), policy_step)
            logger.log("Info/clip_coef", cfg.algo.clip_coef, policy_step)
            logger.log("Info/ent_coef", cfg.algo.ent_coef, policy_step)
            if should_log and not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        # --------------------------------------------------------- annealing
        if cfg.algo.anneal_lr:
            new_lr = polynomial_decay(iter_num, initial=base_lr, final=0.0, max_decay_steps=total_iters, power=1.0)
            opt_state.hyperparams["lr"] = jnp.asarray(new_lr, jnp.float32)
        if cfg.algo.anneal_clip_coef:
            cfg.algo.clip_coef = polynomial_decay(
                iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            cfg.algo.ent_coef = polynomial_decay(
                iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
            )

        # -------------------------------------------------------- checkpoint
        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num == total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "optimizer": opt_state,
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break

    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, params, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()


# ----------------------------------------------------------------------- SAC
def sac_fused_main(runtime, cfg: Dict[str, Any]):
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import _make_optimizer, make_fused_train_step
    from sheeprl_tpu.algos.sac.utils import test
    from sheeprl_tpu.core.runtime import DispatchThrottle

    _reset_run_stats()
    mesh = runtime.mesh
    rank = runtime.global_rank
    world_size = jax.process_count()

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir} (fused Anakin lane)")
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    watchdog = runtime.resilience.watchdog
    health = runtime.health

    env = _resolve_env(cfg)
    num_envs = int(cfg.env.num_envs)
    obs_key, pixel = _single_obs_key(cfg, env)
    if pixel:
        raise ValueError("Only vector observations are supported by the SAC agent")
    observation_space = gym.spaces.Dict({obs_key: env.observation_space})
    action_space = canonical_action_space(env)
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    to_env = action_to_env(env)
    clip_rewards = bool(cfg.env.clip_rewards)
    obs_dim = int(np.prod(env.observation_space.shape))
    act_dim = int(np.prod(action_space.shape))

    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime, cfg, observation_space, action_space,
            state_ckpt["agent"] if state_ckpt is not None else None,
        )
        txs = {
            "qf": _make_optimizer(cfg.algo.critic.optimizer),
            "actor": _make_optimizer(cfg.algo.actor.optimizer),
            "alpha": _make_optimizer(cfg.algo.alpha.optimizer),
        }
        opt_states = {
            "qf": txs["qf"].init(agent_state["qfs"]),
            "actor": txs["actor"].init(agent_state["actor"]),
            "alpha": txs["alpha"].init(agent_state["log_alpha"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (("qf", "qf_optimizer"), ("actor", "actor_optimizer"), ("alpha", "alpha_optimizer")):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])
    agent_state = runtime.shard_params(agent_state)
    opt_states = runtime.shard_params(opt_states)

    # Arm per-shard goodput accounting: the observatory needs the mesh and the
    # realised param layouts to attribute MFU/imbalance per data-shard.
    telemetry.set_mesh(mesh)
    telemetry.record_param_layouts(agent_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    use_shard = _shard_superstep_enabled(cfg, mesh, num_envs)

    # ----------------------------------------------------------------- ring
    # The fused lane is ring-only: transitions are written in-scan and never
    # leave the device, so the ring must allocate up front (and fit HBM).
    # Under the sharded superstep the ring storage itself is sharded over
    # envs: each shard's in-scan writes land on the rows it owns.
    buffer_size = cfg.buffer.size // int(num_envs * world_size) if not cfg.dry_run else 1
    sample_next_obs = bool(cfg.buffer.sample_next_obs)
    ring = DeviceReplayRing(
        buffer_size,
        num_envs,
        obs_keys=("observations",),
        hbm_fraction=float(cfg.buffer.get("device_hbm_fraction", 0.4)),
        device=mesh.devices.flat[0],
        mesh=mesh if use_shard else None,
    )
    specs = {
        "observations": ((obs_dim,), np.float32),
        "actions": ((act_dim,), np.float32),
        "rewards": ((1,), np.float32),
        "terminated": ((1,), np.uint8),
        "truncated": ((1,), np.uint8),
    }
    if not sample_next_obs:
        specs["next_observations"] = ((obs_dim,), np.float32)
    ring.allocate(specs)
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        # A Gymnasium-lane checkpoint carries its host replay buffer: seed
        # the ring with it so the resumed run trains on its history (specs
        # are fixed above, so mismatched host dtypes cast on the way in).
        ring.load_host_buffer(state_ckpt["rb"])
        ring.flush()
    if not ring.active:
        raise RuntimeError(
            f"algo.fused_rollout needs the device replay ring, which declined its "
            f"allocation: {ring.inactive_reason}"
        )
    write_fn = ring.make_step_write_fn()
    ring_sample_fn = ring.make_sample_fn(
        cfg.algo.per_rank_batch_size, sequence_length=1, sample_next_obs=sample_next_obs
    )
    ring_span = 1 + int(sample_next_obs)
    fused_train_fn = make_fused_train_step(
        agent,
        txs,
        cfg,
        mesh,
        ring_sample_fn,
        state=agent_state,
        opt_states=opt_states,
        ring_shardings=ring.state_shardings(),
    )
    fused_train_steps = max(int(cfg.algo.get("fused_train_steps", 1)), 1)

    # ------------------------------------------------------------- counters
    last_train = 0
    train_step_count = 0
    start_iter = (state_ckpt["iter_num"] // world_size) + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])
    target_freq_iters = cfg.algo.critic.target_network_frequency // policy_steps_per_iter + 1
    superstep_iters = max(int(cfg.algo.get("fused_superstep_steps", 64)), 1)

    E = num_envs

    # ------------------------------------------------------------ supersteps
    step_v = jax.vmap(env.step)
    reset_v = jax.vmap(env.reset)

    def _make_rollout(steps: int, random_actions: bool):
        def rollout_core(actor_params, ring_state, env_state, obs, ep_ret, ep_len, k_roll):
            # Local (per-shard) env width: under shard_map each shard traces
            # with its E/data rows (the ring's in-scan writes then touch only
            # the rows this shard owns); unsharded this is just E. Per-env
            # PRNG streams are keyed by the env's GLOBAL id on both paths.
            e_local = obs.shape[0]
            genv = _global_env_ids(e_local, use_shard)

            def body(carry, step_key):
                env_state, obs, ep_ret, ep_len, ring_state = carry
                k_act, k_step, k_reset = jax.random.split(step_key, 3)
                act_keys = _fold_env_keys(k_act, genv)
                if random_actions:
                    # Uniform over the canonical [-1, 1] box == the host
                    # lane's envs.action_space.sample() after RescaleAction,
                    # drawn per env from the env's own key.
                    actions = jax.vmap(
                        lambda k: jax.random.uniform(k, (act_dim,), minval=-1.0, maxval=1.0)
                    )(act_keys)
                else:
                    # Per-env singleton-batch policy call: the exploration
                    # noise comes from the env's own key, so the draw is
                    # independent of how envs are batched across shards.
                    actions = jax.vmap(
                        lambda o, k: agent.get_actions(actor_params, o[None, :], k, greedy=False)[0]
                    )(obs.reshape(e_local, obs_dim), act_keys)
                new_state, new_obs, reward, done, info = step_v(
                    env_state, to_env(actions.reshape((e_local, *action_space.shape))),
                    _fold_env_keys(k_step, genv),
                )
                buf_reward = jnp.tanh(reward) if clip_rewards else reward
                row = {
                    "observations": obs.reshape(e_local, obs_dim),
                    "actions": actions,
                    "rewards": buf_reward[:, None],
                    "terminated": info["terminated"][:, None],
                    "truncated": info["truncated"][:, None],
                }
                if not sample_next_obs:
                    # TRUE next obs (pre-reset): the host lane's real_next_obs.
                    row["next_observations"] = new_obs.reshape(e_local, obs_dim)
                ring_state = write_fn(ring_state, row, jnp.ones((e_local,), jnp.bool_))
                ep_ret = ep_ret + reward
                ep_len = ep_len + 1
                r_state, r_obs = reset_v(_fold_env_keys(k_reset, genv))
                env_state = jax.tree_util.tree_map(
                    lambda r, n: _where_done(done, r, n), r_state, new_state
                )
                obs_next = _where_done(done, r_obs, new_obs)
                ep_info = {"done": done, "returns": ep_ret, "lengths": ep_len.astype(jnp.float32)}
                ep_ret = jnp.where(done, 0.0, ep_ret)
                ep_len = jnp.where(done, 0, ep_len)
                return (env_state, obs_next, ep_ret, ep_len, ring_state), ep_info

            (env_state, obs, ep_ret, ep_len, ring_state), ep_info = jax.lax.scan(
                body, (env_state, obs, ep_ret, ep_len, ring_state), jax.random.split(k_roll, steps)
            )
            return env_state, obs, ep_ret, ep_len, ring_state, ep_info

        core = rollout_core
        if use_shard:
            # SPMD superstep: each shard steps its own envs and writes its own
            # ring rows; no cross-shard traffic inside the scan. check_rep is
            # off because the unmentioned `model` axis (size 1 here) defeats
            # replication inference; params/keys arrive replicated.
            p_env = P(DATA_AXIS)
            ring_specs = jax.tree_util.tree_map(lambda s: s.spec, ring.state_shardings())
            core = shard_map(
                rollout_core,
                mesh=mesh,
                in_specs=(P(), ring_specs, p_env, p_env, p_env, p_env, P()),
                out_specs=(p_env, p_env, p_env, p_env, ring_specs, P(None, DATA_AXIS)),
                check_rep=False,
            )

        def rollout(actor_params, ring_state, env_state, obs, ep_ret, ep_len, key):
            next_key, k_roll = jax.random.split(key)
            env_state, obs, ep_ret, ep_len, ring_state, ep_info = core(
                actor_params, ring_state, env_state, obs, ep_ret, ep_len, k_roll
            )
            return env_state, obs, ep_ret, ep_len, ring_state, ep_info, next_key

        return jax.jit(rollout, donate_argnums=(1, 2, 3, 4, 5))

    rollout_fns: Dict[Tuple[int, bool], Any] = {}

    def _rollout_fn(steps: int, random_actions: bool):
        fn = rollout_fns.get((steps, random_actions))
        if fn is None:
            fn = _make_rollout(steps, random_actions)
            rollout_fns[(steps, random_actions)] = fn
        return fn

    init_key, loop_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key, train_key = jax.random.split(loop_key)
    # Env init is computed from GLOBAL per-env keys (identical on every
    # topology), then the carries land on their `data`-axis shards.
    env_state, obs = jax.jit(reset_v)(jax.random.split(init_key, E))
    ep_ret = jnp.zeros((E,), jnp.float32)
    ep_len = jnp.zeros((E,), jnp.int32)
    if use_shard:
        env_sharding = NamedSharding(mesh, P(DATA_AXIS))
        env_state, obs, ep_ret, ep_len = jax.device_put(
            (env_state, obs, ep_ret, ep_len), env_sharding
        )
    ring_state = ring.state

    cumulative_per_rank_gradient_steps = 0
    dispatch_throttle = DispatchThrottle()
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    perf = telemetry.perf
    keep_train_metrics = (
        aggregator is not None and not aggregator.disabled and cfg.metric.log_level > 0
    ) or health.enabled
    pending_eps: List[Dict[str, Any]] = []
    tracer = tracer_mod.current()

    iter_num = start_iter - 1  # last completed host-lane iteration
    while iter_num < total_iters:
        if iter_num < learning_starts:
            chunk = min(superstep_iters, learning_starts - iter_num, total_iters - iter_num)
            random_phase = True
        else:
            chunk = min(superstep_iters, total_iters - iter_num)
            random_phase = False
        telemetry.advance(policy_step)
        guard.advance(policy_step)
        iter_start = iter_num
        iter_num += chunk
        policy_step += chunk * policy_steps_per_iter

        with timer("Time/env_interaction_time" if random_phase else "Time/train_time"):
            rollout_fn = _rollout_fn(chunk, random_phase)
            # Goodput accounting BEFORE the dispatch (the rollout jit donates
            # its carry).
            perf.note(
                f"rollout/c{chunk}_r{int(random_phase)}", rollout_fn,
                (agent_state["actor"], ring_state, env_state, obs, ep_ret, ep_len, rollout_key),
                steps=0,
            )
            with tracer.span("fused/superstep", "train"), train_timer.step(), watch(
                watchdog, "train_dispatch"
            ):
                env_state, obs, ep_ret, ep_len, ring_state, ep_info, rollout_key = rollout_fn(
                    agent_state["actor"], ring_state, env_state, obs, ep_ret, ep_len, rollout_key
                )
            train_timer.pend(ep_info["done"], None)
        pending_eps.append(ep_info)
        ring.adopt_state(ring_state, chunk)
        ring_state = ring.state
        _RUN_STATS["supersteps"] += 1
        _RUN_STATS["jit_dispatches"] += 1
        _RUN_STATS["env_steps"] += chunk * E

        # ------------------------------------------------------ train phase
        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(
                (policy_step - prefill_steps + policy_steps_per_iter) / world_size
            )
            if per_rank_gradient_steps > 0 and ring.ready(ring_span):
                taus_full = _superstep_taus(
                    iter_start, iter_num, target_freq_iters, float(agent.tau), per_rank_gradient_steps
                )
                with timer("Time/train_time"):
                    remaining = per_rank_gradient_steps
                    offset = 0
                    while remaining > 0:
                        k = 1 << (min(remaining, fused_train_steps).bit_length() - 1)
                        taus_k = taus_full[offset:offset + k]
                        perf.note(
                            f"train/fused_k{k}", fused_train_fn,
                            (agent_state, opt_states, ring_state, train_key, taus_k),
                            steps=k,
                        )
                        with tracer.span("fused/train", "train"), train_timer.step(), watch(
                            watchdog, "train_dispatch"
                        ):
                            agent_state, opt_states, train_metrics, train_key = fused_train_fn(
                                agent_state, opt_states, ring_state, train_key, taus_k,
                            )
                        train_timer.pend(
                            agent_state["actor"], train_metrics if keep_train_metrics else None
                        )
                        dispatch_throttle.add(train_metrics)
                        cumulative_per_rank_gradient_steps += k
                        remaining -= k
                        offset += k
                        _RUN_STATS["jit_dispatches"] += 1
                train_step_count += world_size

        # ----------------------------------------------------------- logging
        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num >= total_iters
        )
        if should_log:
            fetched_train_metrics = train_timer.flush()
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            _log_episode_stats(pending_eps, aggregator, runtime, policy_step, cfg.metric.log_level)
            if aggregator and not aggregator.disabled:
                for tm in fetched_train_metrics:
                    aggregator.update("Loss/value_loss", tm["value_loss"])
                    aggregator.update("Loss/policy_loss", tm["policy_loss"])
                    aggregator.update("Loss/alpha_loss", tm["alpha_loss"])
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            logger.log(
                "Params/replay_ratio", cumulative_per_rank_gradient_steps * world_size / policy_step, policy_step
            )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        # -------------------------------------------------------- checkpoint
        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num >= total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": agent_state,
                "qf_optimizer": opt_states["qf"],
                "actor_optimizer": opt_states["actor"],
                "alpha_optimizer": opt_states["alpha"],
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            # No "rb": the ring is device-resident; the Gymnasium lane
            # tolerates a missing buffer on resume (state_ckpt.get("rb")).
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break

    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, agent_state, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()


# ----------------------------------------------------------------- DreamerV3
def dreamer_v3_fused_main(runtime, cfg: Dict[str, Any]):
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
        _make_optimizer,
        _target_update_taus,
        make_fused_train_step,
    )
    from sheeprl_tpu.algos.dreamer_v3.utils import normalize_player_obs, test
    from sheeprl_tpu.algos.ppo.agent import actions_metadata
    from sheeprl_tpu.core.runtime import DispatchThrottle
    from sheeprl_tpu.utils.ops import init_moments

    _reset_run_stats()
    mesh = runtime.mesh
    rank = runtime.global_rank
    world_size = jax.process_count()

    state_ckpt = None
    if cfg.checkpoint.resume_from:
        state_ckpt = load_checkpoint(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.print(f"Log dir: {log_dir} (fused Anakin lane)")
    telemetry = runtime.telemetry.open(log_dir, rank_zero=runtime.is_global_zero, device=runtime.device)
    guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
    watchdog = runtime.resilience.watchdog
    health = runtime.health

    env = _resolve_env(cfg)
    num_envs = int(cfg.env.num_envs)
    obs_key, pixel = _single_obs_key(cfg, env)
    observation_space = gym.spaces.Dict({obs_key: env.observation_space})
    action_space = canonical_action_space(env)
    actions_dim, is_continuous = actions_metadata(action_space)
    act_sum = int(np.sum(actions_dim))
    to_env = action_to_env(env)
    clip_rewards = bool(cfg.env.clip_rewards)
    cnn_keys = (obs_key,) if pixel else ()
    obs_keys = [obs_key]

    with runtime.host_init():
        agent, agent_state = build_agent(
            runtime,
            actions_dim,
            is_continuous,
            cfg,
            observation_space,
            state_ckpt["world_model"] if state_ckpt is not None else None,
            state_ckpt["actor"] if state_ckpt is not None else None,
            state_ckpt["critic"] if state_ckpt is not None else None,
            state_ckpt["target_critic"] if state_ckpt is not None else None,
        )
        txs = {
            "world_model": _make_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients),
            "actor": _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients),
            "critic": _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients),
        }
        opt_states = {
            "world_model": txs["world_model"].init(agent_state["world_model"]),
            "actor": txs["actor"].init(agent_state["actor"]),
            "critic": txs["critic"].init(agent_state["critic"]),
        }
        if state_ckpt is not None:
            for name, ckpt_key in (
                ("world_model", "world_optimizer"),
                ("actor", "actor_optimizer"),
                ("critic", "critic_optimizer"),
            ):
                opt_states[name] = restore_opt_state(opt_states[name], state_ckpt[ckpt_key])
    agent_state = runtime.shard_params(agent_state)
    opt_states = runtime.shard_params(opt_states)

    # Arm per-shard goodput accounting: the observatory needs the mesh and the
    # realised param layouts to attribute MFU/imbalance per data-shard.
    telemetry.set_mesh(mesh)
    telemetry.record_param_layouts(agent_state)

    moments_state = init_moments()
    if state_ckpt is not None and "moments" in state_ckpt:
        moments_state = jax.tree_util.tree_map(jnp.asarray, state_ckpt["moments"])

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    # ----------------------------------------------------------------- ring
    # Dreamer's superstep keeps GSPMD placement (the recurrent player latents
    # and sparse reset rows make its carry sharding XLA's call); the ring is
    # still env-sharded so the fused train jit samples per-shard minibatches.
    buffer_size = cfg.buffer.size // int(num_envs * world_size) if not cfg.dry_run else 2
    ring = DeviceReplayRing(
        buffer_size,
        num_envs,
        cnn_keys=cnn_keys,
        obs_keys=tuple(obs_keys),
        hbm_fraction=float(cfg.buffer.get("device_hbm_fraction", 0.4)),
        device=mesh.devices.flat[0],
        mesh=mesh,
    )
    obs_dtype = np.uint8 if pixel else np.float32
    specs = {
        obs_key: (tuple(env.observation_space.shape), obs_dtype),
        "actions": ((act_sum,), np.float32),
        "rewards": ((1,), np.float32),
        "terminated": ((1,), np.float32),
        "truncated": ((1,), np.float32),
        "is_first": ((1,), np.float32),
    }
    ring.allocate(specs)
    if state_ckpt is not None and cfg.buffer.checkpoint and state_ckpt.get("rb") is not None:
        ring.load_host_buffer(state_ckpt["rb"])
        ring.flush()
    if not ring.active:
        raise RuntimeError(
            f"algo.fused_rollout needs the device replay ring, which declined its "
            f"allocation: {ring.inactive_reason}"
        )
    write_fn = ring.make_step_write_fn()
    ring_sample_fn = ring.make_sample_fn(
        cfg.algo.per_rank_batch_size,
        sequence_length=cfg.algo.per_rank_sequence_length,
        time_major=True,
    )
    fused_train_fn = make_fused_train_step(
        agent,
        txs,
        cfg,
        mesh,
        ring_sample_fn,
        state=agent_state,
        opt_states=opt_states,
        ring_shardings=ring.state_shardings(),
    )
    fused_train_steps = max(int(cfg.algo.get("fused_train_steps", 1)), 1)

    # ------------------------------------------------------------- counters
    last_train = 0
    train_step_count = 0
    start_iter = (state_ckpt["iter_num"] // world_size) + 1 if state_ckpt is not None else 1
    policy_step = state_ckpt["iter_num"] * num_envs if state_ckpt is not None else 0
    last_log = state_ckpt["last_log"] if state_ckpt is not None else 0
    last_checkpoint = state_ckpt["last_checkpoint"] if state_ckpt is not None else 0
    policy_steps_per_iter = int(num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state_ckpt is not None:
        cfg.algo.per_rank_batch_size = state_ckpt["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state_ckpt is not None:
        ratio.load_state_dict(state_ckpt["ratio"])
    superstep_iters = max(int(cfg.algo.get("fused_superstep_steps", 16)), 1)

    E = num_envs

    # ------------------------------------------------------------ supersteps
    step_v = jax.vmap(env.step)
    reset_v = jax.vmap(env.reset)

    def _make_rollout(steps: int, random_actions: bool):
        def rollout(wm_params, actor_params, player_state, env_state, obs, prev, ep_ret, ep_len, ring_state, key):
            next_key, k_roll = jax.random.split(key)

            def body(carry, step_key):
                env_state, obs, player_state, prev, ep_ret, ep_len, ring_state = carry
                k_act, k_step, k_reset = jax.random.split(step_key, 3)
                if random_actions:
                    if is_continuous:
                        actions_cat = jax.random.uniform(k_act, (E, act_sum), minval=-1.0, maxval=1.0)
                        real_actions = actions_cat
                    else:
                        subkeys = jax.random.split(k_act, len(actions_dim))
                        parts, reals = [], []
                        for ad, sk in zip(actions_dim, subkeys):
                            idx = jax.random.randint(sk, (E,), 0, ad)
                            parts.append(jax.nn.one_hot(idx, ad, dtype=jnp.float32))
                            reals.append(idx)
                        actions_cat = jnp.concatenate(parts, -1)
                        real_actions = jnp.stack(reals, -1)
                else:
                    actions_cat, real_actions, player_state = agent.player_step(
                        wm_params, actor_params, player_state,
                        normalize_player_obs({obs_key: obs}, cnn_keys), k_act, greedy=False,
                    )
                # Dreamer row convention: step t's row = (obs_t, action_t,
                # reward_{t-1}, flags_{t-1}, is_first) — exactly the host
                # lane's step_data ordering.
                row = dict(prev)
                row[obs_key] = obs
                row["actions"] = actions_cat.astype(jnp.float32)
                ring_state = write_fn(ring_state, row, jnp.ones((E,), jnp.bool_))
                new_state, new_obs, reward, done, info = step_v(
                    env_state, _env_actions(real_actions, env, to_env, is_continuous, E),
                    jax.random.split(k_step, E),
                )
                buf_reward = (jnp.tanh(reward) if clip_rewards else reward)[:, None]
                terminated = info["terminated"][:, None].astype(jnp.float32)
                truncated = info["truncated"][:, None].astype(jnp.float32)
                # Episode-boundary reset row (host lane's reset_data): the
                # TRUE final obs + the real flags + this step's reward.
                reset_row = {
                    obs_key: new_obs,
                    "actions": jnp.zeros((E, act_sum), jnp.float32),
                    "rewards": buf_reward,
                    "terminated": terminated,
                    "truncated": truncated,
                    "is_first": jnp.zeros((E, 1), jnp.float32),
                }
                ring_state = write_fn(ring_state, reset_row, done)
                d1 = done[:, None].astype(jnp.float32)
                prev = {
                    "rewards": (1.0 - d1) * buf_reward,
                    "terminated": (1.0 - d1) * terminated,
                    "truncated": (1.0 - d1) * truncated,
                    "is_first": d1,
                }
                if not random_actions:
                    player_state = agent.reset_player_state(
                        wm_params, player_state, done.astype(jnp.float32)
                    )
                ep_ret = ep_ret + reward
                ep_len = ep_len + 1
                r_state, r_obs = reset_v(jax.random.split(k_reset, E))
                env_state = jax.tree_util.tree_map(
                    lambda r, n: _where_done(done, r, n), r_state, new_state
                )
                obs_next = _where_done(done, r_obs, new_obs)
                ep_info = {"done": done, "returns": ep_ret, "lengths": ep_len.astype(jnp.float32)}
                ep_ret = jnp.where(done, 0.0, ep_ret)
                ep_len = jnp.where(done, 0, ep_len)
                return (env_state, obs_next, player_state, prev, ep_ret, ep_len, ring_state), ep_info

            (env_state, obs, player_state, prev, ep_ret, ep_len, ring_state), ep_info = jax.lax.scan(
                body, (env_state, obs, player_state, prev, ep_ret, ep_len, ring_state),
                jax.random.split(k_roll, steps),
            )
            rows_written = steps + ep_info["done"].astype(jnp.int32).sum(0)
            return env_state, obs, player_state, prev, ep_ret, ep_len, ring_state, ep_info, rows_written, next_key

        return jax.jit(rollout, donate_argnums=(2, 3, 4, 5, 6, 7, 8))

    rollout_fns: Dict[Tuple[int, bool], Any] = {}

    def _rollout_fn(steps: int, random_actions: bool):
        fn = rollout_fns.get((steps, random_actions))
        if fn is None:
            fn = _make_rollout(steps, random_actions)
            rollout_fns[(steps, random_actions)] = fn
        return fn

    init_key, loop_key = jax.random.split(jax.random.fold_in(runtime.root_key, rank))
    rollout_key, train_key = jax.random.split(loop_key)
    env_state, obs = jax.jit(reset_v)(jax.random.split(init_key, E))
    player_state = jax.jit(agent.init_player_state, static_argnums=(1,))(agent_state["world_model"], E)
    prev = {
        "rewards": jnp.zeros((E, 1), jnp.float32),
        "terminated": jnp.zeros((E, 1), jnp.float32),
        "truncated": jnp.zeros((E, 1), jnp.float32),
        "is_first": jnp.ones((E, 1), jnp.float32),
    }
    ep_ret = jnp.zeros((E,), jnp.float32)
    ep_len = jnp.zeros((E,), jnp.int32)
    ring_state = ring.state

    cumulative_per_rank_gradient_steps = 0
    dispatch_throttle = DispatchThrottle()
    train_timer = telemetry.step_timer("train", timer_key="Time/train_time")
    perf = telemetry.perf
    keep_train_metrics = (
        aggregator is not None and not aggregator.disabled and cfg.metric.log_level > 0
    ) or health.enabled
    pending_eps: List[Dict[str, Any]] = []
    tracer = tracer_mod.current()
    resumed = state_ckpt is not None

    iter_num = start_iter - 1  # last completed host-lane iteration
    while iter_num < total_iters:
        random_phase = iter_num < learning_starts and not resumed
        bound = total_iters - iter_num
        if iter_num < learning_starts:
            # Never straddle the learning_starts boundary: training begins
            # exactly where the host lane's does.
            bound = min(bound, learning_starts - iter_num)
        chunk = min(superstep_iters, bound)
        telemetry.advance(policy_step)
        guard.advance(policy_step)
        iter_num += chunk
        policy_step += chunk * policy_steps_per_iter

        with timer("Time/env_interaction_time" if random_phase else "Time/train_time"):
            rollout_fn = _rollout_fn(chunk, random_phase)
            # Goodput accounting BEFORE the dispatch (the rollout jit donates
            # its carry).
            perf.note(
                f"rollout/c{chunk}_r{int(random_phase)}", rollout_fn,
                (agent_state["world_model"], agent_state["actor"], player_state,
                 env_state, obs, prev, ep_ret, ep_len, ring_state, rollout_key),
                steps=0,
            )
            with tracer.span("fused/superstep", "train"), train_timer.step(), watch(
                watchdog, "train_dispatch"
            ):
                (
                    env_state, obs, player_state, prev, ep_ret, ep_len, ring_state, ep_info,
                    rows_written, rollout_key,
                ) = rollout_fn(
                    agent_state["world_model"], agent_state["actor"], player_state,
                    env_state, obs, prev, ep_ret, ep_len, ring_state, rollout_key,
                )
            train_timer.pend(ep_info["done"], None)
        pending_eps.append(ep_info)
        ring.adopt_state(ring_state, _fetch_row_counts(rows_written))
        ring_state = ring.state
        _RUN_STATS["supersteps"] += 1
        _RUN_STATS["jit_dispatches"] += 1
        _RUN_STATS["env_steps"] += chunk * E

        # ------------------------------------------------------ train phase
        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0 and ring.ready(cfg.algo.per_rank_sequence_length):
                with timer("Time/train_time"):
                    remaining = per_rank_gradient_steps
                    while remaining > 0:
                        k = 1 << (min(remaining, fused_train_steps).bit_length() - 1)
                        taus = _target_update_taus(
                            cumulative_per_rank_gradient_steps,
                            k,
                            cfg.algo.critic.per_rank_target_network_update_freq,
                            cfg.algo.critic.tau,
                        )
                        perf.note(
                            f"train/fused_k{k}", fused_train_fn,
                            (agent_state, opt_states, moments_state, ring_state, train_key, taus),
                            steps=k,
                        )
                        with tracer.span("fused/train", "train"), train_timer.step(), watch(
                            watchdog, "train_dispatch"
                        ):
                            agent_state, opt_states, moments_state, train_metrics, train_key = fused_train_fn(
                                agent_state, opt_states, moments_state, ring_state, train_key, taus
                            )
                        train_timer.pend(
                            agent_state["world_model"], train_metrics if keep_train_metrics else None
                        )
                        dispatch_throttle.add(train_metrics)
                        cumulative_per_rank_gradient_steps += k
                        remaining -= k
                        _RUN_STATS["jit_dispatches"] += 1
                train_step_count += world_size

        # ----------------------------------------------------------- logging
        should_log = cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num >= total_iters
        )
        if should_log:
            fetched_train_metrics = train_timer.flush()
            health.observe(policy_step, fetched_train_metrics, telemetry=telemetry)
            _log_episode_stats(pending_eps, aggregator, runtime, policy_step, cfg.metric.log_level)
            if aggregator and not aggregator.disabled:
                for m in fetched_train_metrics:
                    for mk, v in m.items():
                        if mk in aggregator:
                            aggregator.update(mk, v)
                aggregator.log_and_reset(logger, policy_step)
            telemetry.log_counters(logger, policy_step)
        if should_log and logger is not None:
            if policy_step > 0:
                logger.log(
                    "Params/replay_ratio",
                    cumulative_per_rank_gradient_steps * world_size / policy_step,
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log(
                        "Time/sps_train",
                        (train_step_count - last_train) / timer_metrics["Time/train_time"],
                        policy_step,
                    )
                timer.reset()
        if should_log:
            last_log = policy_step
            last_train = train_step_count

        # -------------------------------------------------------- checkpoint
        if health.allow_save() and (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or ((iter_num >= total_iters or guard.preempted) and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": agent_state["world_model"],
                "actor": agent_state["actor"],
                "critic": agent_state["critic"],
                "target_critic": agent_state["target_critic"],
                "world_optimizer": opt_states["world_model"],
                "actor_optimizer": opt_states["actor"],
                "critic_optimizer": opt_states["critic"],
                "moments": moments_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            if runtime.is_global_zero:
                save_checkpoint(ckpt_path, ckpt_state, keep_last=cfg.checkpoint.keep_last)

        if guard.preempted:
            runtime.print(f"Preemption: exiting cleanly after final checkpoint at policy step {policy_step}")
            break

    if runtime.is_global_zero and cfg.algo.run_test and not guard.preempted:
        test(agent, agent_state, runtime, cfg, log_dir, logger)

    guard.close()
    telemetry.close()
    if logger is not None:
        logger.close()
