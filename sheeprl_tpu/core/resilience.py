"""sheeprl_tpu.core.resilience — fault tolerance for preemptible training.

The north-star deployment is Podracer-style (arXiv:2104.06272): long-lived
learners on preemptible TPU pods that survive because snapshots are cheap,
atomic, and always resumable, with env workers treated as a managed pool
(EnvPool, arXiv:2206.10558) rather than bare processes. This module is the
host-side half of that story; the storage half (atomic manifest-committed
checkpoints, `find_latest_valid_checkpoint`) lives in
``sheeprl_tpu/utils/checkpoint.py``.

Three cooperating pieces, all config-driven via the ``resilience`` Hydra
group and surfaced to train loops through ``runtime.resilience``:

- :class:`PreemptionGuard` — catches SIGTERM/SIGINT on the main thread and
  turns them into a *clean exit at the next iteration boundary*: the loop's
  normal end-of-iteration path already harvests pending fetches and the
  checkpoint write blocks on every in-flight donated dispatch (``np.asarray``
  on device leaves), so the boundary IS the drain. The guard forces a final
  checkpoint (every loop's save condition includes ``guard.preempted``),
  learns about it through the checkpoint post-save hook, and writes an
  atomic ``autoresume.json`` pointer next to it so the replacement process
  can resume with ``checkpoint.resume_from=auto:<dir>``.

- :class:`EnvSupervisor` — an :class:`EnvSliceGroup` whose ``step_slice``
  catches env-step exceptions and dead subprocesses, rebuilds the failing
  slice from its factory with exponential backoff + jitter, reseeds it
  deterministically from the run seed + restart count, and reports the
  restart as a *truncated* episode boundary so the poisoned in-flight
  episode is dropped at sequence sampling (at most one episode of data
  lost). A slice that exhausts ``max_restarts`` trips the circuit breaker:
  the sole slice raises; one of many is masked out (zero obs, truncated
  rows) so the rest of the rollout keeps learning.

- :class:`DispatchWatchdog` — a monotonic-deadline watchdog armed around
  donated train dispatches and blocking action fetches. A hang past the
  deadline logs a full thread stack dump (the dispatch is unobservable from
  inside — the stack tells you which device call wedged), counts
  ``watchdog_trips``, and per config warns, delivers SIGTERM to reuse the
  clean preemption path, or aborts the process.

Fault injection for all of the above lives in ``sheeprl_tpu/core/chaos.py``.
"""

from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import threading
import time
import warnings
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.core import chaos
from sheeprl_tpu.core.interact import EnvSliceGroup
from sheeprl_tpu.telemetry import tracer as tracer_mod

__all__ = [
    "AUTORESUME_NAME",
    "DispatchWatchdog",
    "EnvSupervisor",
    "PreemptionGuard",
    "Resilience",
    "apply_trip_policy",
    "resolve_auto_resume",
    "watch",
]

AUTORESUME_NAME = "autoresume.json"


def _cfg_get(section: Any, key: str, default: Any) -> Any:
    if section is None:
        return default
    getter = getattr(section, "get", None)
    if getter is not None:
        value = getter(key, default)
    else:
        value = getattr(section, key, default)
    return default if value is None else value


# ------------------------------------------------------------ PreemptionGuard
class PreemptionGuard:
    """Main-thread SIGTERM/SIGINT -> clean checkpoint-and-exit.

    Lifecycle (one guard per train loop run)::

        guard = runtime.resilience.guard(rank_zero=runtime.is_global_zero)
        for iter_num in ...:
            guard.advance(policy_step)       # also pulses chaos injectors
            ...
            # save condition includes `guard.preempted` -> final snapshot
            if guard.preempted:
                break                        # iteration boundary == drained
        guard.close()                        # restores previous handlers

    The guard never does work inside the signal handler beyond flag + counter
    (async-signal-safe-ish by construction); everything expensive happens at
    the iteration boundary on the main thread. A second SIGINT re-raises
    KeyboardInterrupt so an impatient Ctrl-C Ctrl-C still kills the run.

    Handler install/restore is strictly scoped: tests run many algorithm
    mains in one process and each must leave signal disposition as found.
    """

    def __init__(
        self,
        *,
        enabled: bool = True,
        catch_sigint: bool = True,
        write_pointer: bool = True,
        rank_zero: bool = True,
        chaos_monkey: Optional[chaos.ChaosMonkey] = None,
        on_close: Optional[Callable[[], None]] = None,
    ) -> None:
        self._enabled = bool(enabled)
        self._signals: Tuple[int, ...] = (
            (signal.SIGTERM, signal.SIGINT) if catch_sigint else (signal.SIGTERM,)
        )
        self._write_pointer = bool(write_pointer)
        self._rank_zero = bool(rank_zero)
        self._chaos = chaos_monkey
        self._on_close = on_close
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self._hook_registered = False
        self._preempted = False
        self._signum: Optional[int] = None
        self._policy_step = 0
        self.last_checkpoint_path: Optional[str] = None

    # ------------------------------------------------------------- install
    def install(self) -> "PreemptionGuard":
        if not self._enabled or self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            # Signal handlers can only live on the main thread (decoupled
            # trainer threads, test runners): the guard still drives chaos
            # injectors and checkpoint pointers, just not signals.
            self._signals = ()
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        from sheeprl_tpu.utils import checkpoint as ckpt_mod

        ckpt_mod.register_post_save_hook(self._on_save)
        self._hook_registered = True
        self._installed = True
        return self

    def close(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        if self._hook_registered:
            from sheeprl_tpu.utils import checkpoint as ckpt_mod

            ckpt_mod.unregister_post_save_hook(self._on_save)
            self._hook_registered = False
        self._installed = False
        if self._on_close is not None:
            self._on_close()

    # ------------------------------------------------------------ signals
    def _handle(self, signum: int, frame: Any) -> None:
        if self._preempted and signum == signal.SIGINT:
            raise KeyboardInterrupt
        first = not self._preempted
        self._preempted = True
        self._signum = signum
        if first:
            tracer_mod.current().count("preemptions")

    @property
    def preempted(self) -> bool:
        return self._preempted

    def advance(self, policy_step: int) -> None:
        """Once per train-loop iteration; also pulses step-driven chaos
        injectors (SIGTERM-at-step-N lands here, at an iteration boundary —
        exactly where a real preemption notice is observed)."""
        self._policy_step = int(policy_step)
        if self._chaos is not None:
            self._chaos.on_step(policy_step)

    # ------------------------------------------------------ save awareness
    def _on_save(self, ckpt_path: str) -> None:
        self.last_checkpoint_path = ckpt_path
        if not self._preempted:
            return
        tracer = tracer_mod.current()
        tracer.count("preemption_saves")
        start = time.perf_counter()
        if self._write_pointer and self._rank_zero:
            self._write_pointer_file(ckpt_path)
        tracer.add_span(
            "resilience/preemption_save", "checkpoint", start,
            time.perf_counter() - start,
            {"step": self._policy_step, "signal": int(self._signum or 0)},
        )
        # The drain checkpoint is the last quiet moment before exit: capture
        # the flight record of the run's final seconds alongside it.
        from sheeprl_tpu.telemetry import flight as flight_mod

        flight_mod.dump_on_trip(
            "resilience/preemption",
            message=f"preemption drain at step {self._policy_step}",
            args={"step": self._policy_step, "ckpt_path": ckpt_path},
        )

    def _write_pointer_file(self, ckpt_path: str) -> None:
        pointer = os.path.join(os.path.dirname(os.path.abspath(ckpt_path)), AUTORESUME_NAME)
        payload = {
            "ckpt_path": os.path.abspath(ckpt_path),
            "policy_step": self._policy_step,
            "signal": int(self._signum or 0),
            "written_unix": time.time(),
        }
        tmp = f"{pointer}.tmp-{os.getpid()}"
        with open(tmp, "w") as fp:
            json.dump(payload, fp, indent=2)
            fp.flush()
            os.fsync(fp.fileno())
        os.replace(tmp, pointer)


# ----------------------------------------------------------- auto-resume
def resolve_auto_resume(spec: str, search_root: Optional[str] = None) -> Optional[str]:
    """Resolve ``checkpoint.resume_from=auto[:<dir>]`` to a checkpoint path.

    Preference order: the newest ``autoresume.json`` pointer under the
    search root whose target still validates (a preempted run's explicit
    hand-off), else the newest manifest-valid checkpoint in any
    ``checkpoint/`` directory under the root. Returns None when nothing
    valid exists.
    """
    from sheeprl_tpu.utils.checkpoint import (
        find_latest_valid_checkpoint,
        parse_ckpt_name,
        validate_checkpoint,
    )

    root = spec.split(":", 1)[1] if ":" in spec else (search_root or os.getcwd())
    root = os.path.abspath(os.path.expanduser(root))
    if not os.path.isdir(root):
        return None

    pointers: List[Tuple[float, str]] = []
    ckpt_dirs: List[str] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if AUTORESUME_NAME in filenames:
            full = os.path.join(dirpath, AUTORESUME_NAME)
            try:
                pointers.append((os.path.getmtime(full), full))
            except OSError:
                pass
        if os.path.basename(dirpath) == "checkpoint":
            ckpt_dirs.append(dirpath)
    for _, pointer in sorted(pointers, reverse=True):
        try:
            with open(pointer) as fp:
                target = json.load(fp).get("ckpt_path")
        except (OSError, ValueError):
            continue
        if target and validate_checkpoint(target):
            return target
    best: Optional[Tuple[int, str]] = None
    for ckpt_dir in ckpt_dirs:
        found = find_latest_valid_checkpoint(ckpt_dir)
        if found is None:
            continue
        parsed = parse_ckpt_name(found)
        step = parsed[0] if parsed else 0
        if best is None or step > best[0]:
            best = (step, found)
    return best[1] if best else None


# ------------------------------------------------------------ EnvSupervisor
class _SliceSlot:
    __slots__ = ("restarts", "dead", "zero_obs")

    def __init__(self) -> None:
        self.restarts = 0
        self.dead = False
        self.zero_obs: Any = None


class EnvSupervisor(EnvSliceGroup):
    """An EnvSliceGroup that keeps stepping when a slice dies.

    ``factories[k]()`` must rebuild sub vector env k from scratch (fresh
    subprocesses included). Recovery semantics: the restarted slice comes
    back *reset*, its step reported as rewards 0 / ``truncated=True`` with
    ``info["env_restarted"]`` set — an episode boundary, so sequence
    samplers never stitch across the crash and at most the poisoned
    in-flight episode is lost. Restart seeds derive deterministically from
    ``(seed, slice, restart_count)`` so a chaos-injected crash replays
    bit-identically.
    """

    def __init__(
        self,
        envs: Sequence[Any],
        factories: Sequence[Callable[[], Any]],
        *,
        seed: int = 0,
        max_restarts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 5.0,
        backoff_jitter: float = 0.25,
    ) -> None:
        super().__init__(envs)
        if len(factories) != len(self.envs):
            raise ValueError("EnvSupervisor needs one factory per slice")
        self._factories: List[Callable[[], Any]] = list(factories)
        self._slots = [_SliceSlot() for _ in self.envs]
        self._seed = int(seed)
        self._max_restarts = int(max_restarts)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._backoff_jitter = float(backoff_jitter)
        self._jitter_rng = np.random.default_rng(self._seed)

    # ------------------------------------------------------------ stepping
    def step_slice(self, k: int, actions: Any) -> Tuple[Any, Any, Any, Any, Dict[str, Any]]:
        if self._slots[k].dead:
            return self._masked_step(k)
        try:
            return self.envs[k].step(actions)
        except Exception as exc:  # noqa: BLE001 - any worker death lands here
            return self._recover(k, exc)

    def reset(
        self, *, seed: Optional[Any] = None, options: Optional[dict] = None
    ) -> Tuple[Any, Dict[str, Any]]:
        from sheeprl_tpu.core.interact import merge_infos, tree_concat

        obs_parts: List[Any] = []
        info_parts: List[Dict[str, Any]] = []
        for k, ((s0, s1), env) in enumerate(zip(self.slice_ranges, self.envs)):
            if isinstance(seed, int):
                sub_seed: Optional[Any] = seed + s0
            elif isinstance(seed, (list, tuple)):
                sub_seed = list(seed[s0:s1])
            else:
                sub_seed = seed
            if self._slots[k].dead:
                out = self._masked_step(k)
                obs, info = out[0], out[4]
            else:
                try:
                    obs, info = env.reset(seed=sub_seed, options=options)
                except Exception as exc:  # noqa: BLE001
                    out = self._recover(k, exc)
                    obs, info = out[0], out[4]
            obs_parts.append(obs)
            info_parts.append(info)
        return tree_concat(obs_parts), merge_infos(info_parts, self.slice_counts)

    def close(self, **kwargs: Any) -> None:
        for env in self.envs:
            try:
                env.close(**kwargs)
            except Exception:  # noqa: BLE001 - dead workers must not block exit
                pass

    # ------------------------------------------------------------ recovery
    def restart_seed(self, k: int, restart: int) -> int:
        """Deterministic reseed for slice k's restart-th rebuild — derived
        from the run seed's stream, never wall clock, so chaos scenarios
        replay exactly."""
        return int(np.random.SeedSequence([self._seed, k, restart]).generate_state(1)[0] % (2**31 - 1))

    def _backoff_s(self, restart: int) -> float:
        base = min(self._backoff_base_s * (2 ** (restart - 1)), self._backoff_max_s)
        return base * (1.0 + self._backoff_jitter * float(self._jitter_rng.random()))

    def _recover(self, k: int, exc: BaseException) -> Tuple[Any, Any, Any, Any, Dict[str, Any]]:
        tracer = tracer_mod.current()
        slot = self._slots[k]
        last_exc = exc
        while slot.restarts < self._max_restarts:
            slot.restarts += 1
            delay = self._backoff_s(slot.restarts)
            warnings.warn(
                f"Env slice {k} failed ({type(last_exc).__name__}: {last_exc}); "
                f"restart {slot.restarts}/{self._max_restarts} after {delay * 1e3:.0f}ms backoff"
            )
            time.sleep(delay)
            try:
                try:
                    self.envs[k].close()
                except Exception:  # noqa: BLE001 - the slice is already broken
                    pass
                start = time.perf_counter()
                env = self._factories[k]()
                obs, info = env.reset(seed=self.restart_seed(k, slot.restarts))
                self.envs[k] = env
                tracer.count("env_restarts")
                tracer.add_span(
                    "resilience/env_restart", "env", start, time.perf_counter() - start,
                    {"slice": k, "restart": slot.restarts},
                )
                n = self.slice_counts[k]
                info = dict(info)
                info["env_restarted"] = np.ones(n, dtype=bool)
                info["_env_restarted"] = np.ones(n, dtype=bool)
                # Rewards 0, truncated=True: the crash point becomes an
                # episode boundary, dropping the poisoned in-flight episode.
                return (
                    obs,
                    np.zeros(n, dtype=np.float64),
                    np.zeros(n, dtype=np.bool_),
                    np.ones(n, dtype=np.bool_),
                    info,
                )
            except Exception as rebuild_exc:  # noqa: BLE001
                last_exc = rebuild_exc
        # Circuit breaker tripped.
        if self.slices == 1:
            raise RuntimeError(
                f"Env slice {k} exceeded max_restarts={self._max_restarts} and it is the "
                f"only slice — cannot degrade, giving up"
            ) from last_exc
        slot.dead = True
        tracer.count("env_slices_dead")
        warnings.warn(
            f"Env slice {k} exceeded max_restarts={self._max_restarts}: masking it out "
            f"of the rollout (remaining slices keep training)"
        )
        return self._masked_step(k)

    def _masked_step(self, k: int) -> Tuple[Any, Any, Any, Any, Dict[str, Any]]:
        import gymnasium as gym

        slot = self._slots[k]
        n = self.slice_counts[k]
        if slot.zero_obs is None:
            slot.zero_obs = gym.vector.utils.create_empty_array(
                self.single_observation_space, n, fn=np.zeros
            )
        info = {
            "env_masked": np.ones(n, dtype=np.bool_),
            "_env_masked": np.ones(n, dtype=np.bool_),
        }
        # Every masked row is truncated: zero-reward one-step episodes that
        # no sequence sampler will chain across.
        return (
            slot.zero_obs,
            np.zeros(n, dtype=np.float64),
            np.zeros(n, dtype=np.bool_),
            np.ones(n, dtype=np.bool_),
            info,
        )

    @property
    def dead_slices(self) -> List[int]:
        return [k for k, slot in enumerate(self._slots) if slot.dead]

    @property
    def restart_counts(self) -> List[int]:
        return [slot.restarts for slot in self._slots]


# --------------------------------------------------------- trip escalation
def apply_trip_policy(
    policy: str,
    message: str,
    *,
    counter: str,
    span_name: str,
    category: str,
    args: Optional[Dict[str, Any]] = None,
    dump_stacks: bool = True,
) -> None:
    """The shared warn|preempt|abort escalation used by every host-side
    sentinel (the dispatch watchdog, the training-health monitor): count the
    trip, record a zero-duration span, write the message to stderr, and then
    act — ``warn`` only reports, ``preempt`` delivers SIGTERM so the
    PreemptionGuard drain→atomic-save→autoresume path runs, ``abort``
    hard-exits with code 124. ``dump_stacks`` adds the full all-thread
    faulthandler dump (the forensics a *hung* dispatch needs; numeric
    sentinels pass False — the stack is not the story for a NaN)."""
    tracer = tracer_mod.current()
    tracer.count(counter)
    tracer.add_span(
        span_name, category, time.perf_counter(), 0.0,
        dict(args or {}, policy=policy),
    )
    sys.stderr.write(f"\n{message}\n")
    sys.stderr.flush()
    if dump_stacks:
        try:
            faulthandler.dump_traceback(all_threads=True)
        except Exception:  # noqa: BLE001 - forensics must not kill the caller
            pass
    # Flight dump BEFORE the policy acts: preempt/abort may end the process,
    # and the merged dump (this process + every spilled worker) is the
    # post-mortem record of what tripped.
    from sheeprl_tpu.telemetry import flight as flight_mod

    flight_mod.dump_on_trip(span_name, message=message, args=dict(args or {}, policy=policy))
    if policy == "preempt":
        os.kill(os.getpid(), signal.SIGTERM)
    elif policy == "abort":
        os._exit(124)


# ---------------------------------------------------------- DispatchWatchdog
class DispatchWatchdog:
    """Monotonic-deadline watchdog for device work the host can't observe.

    Arm around a donated train dispatch or a blocking fetch::

        with watchdog.guard("train_dispatch"):
            state = train_fn(state, batch)   # wedged XLA call -> trip

    On trip (deadline exceeded while armed): ``watchdog_trips`` counter, a
    telemetry span, a message + full ``faulthandler`` all-thread stack dump
    to stderr (the only forensics available for a hung device call), then
    per ``on_trip``: ``"warn"`` keeps waiting, ``"preempt"`` delivers
    SIGTERM to the process so the PreemptionGuard path checkpoints and
    exits, ``"abort"`` hard-exits (exit code 124, after the dump). One trip
    per armed window. The monitor thread is lazy (first guard) and a
    daemon, and `close()` joins it."""

    def __init__(self, *, timeout_s: float = 120.0, on_trip: str = "warn") -> None:
        if on_trip not in ("warn", "preempt", "abort"):
            raise ValueError(f"watchdog on_trip must be warn|preempt|abort, got {on_trip!r}")
        self.timeout_s = float(timeout_s)
        self.on_trip = on_trip
        self.trips = 0
        self._cond = threading.Condition()
        self._deadline: Optional[float] = None
        self._label = ""
        self._gen = 0
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    @contextmanager
    def guard(self, label: str = "dispatch"):
        if self.timeout_s <= 0 or self._closed:
            yield
            return
        gen = self._arm(label)
        try:
            yield
        finally:
            self._disarm(gen)

    def _arm(self, label: str) -> int:
        with self._cond:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="sheeprl-dispatch-watchdog", daemon=True
                )
                self._thread.start()
            self._gen += 1
            self._label = label
            self._deadline = time.monotonic() + self.timeout_s
            self._cond.notify_all()
            return self._gen

    def _disarm(self, gen: int) -> None:
        with self._cond:
            if self._gen == gen:
                self._deadline = None
                self._label = ""
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._closed and (
                    self._deadline is None or time.monotonic() < self._deadline
                ):
                    if self._deadline is None:
                        self._cond.wait()
                    else:
                        self._cond.wait(max(0.0, self._deadline - time.monotonic()))
                if self._closed:
                    return
                label = self._label
                self._deadline = None  # one trip per armed window
            self._trip(label)

    def _trip(self, label: str) -> None:
        self.trips += 1
        apply_trip_policy(
            self.on_trip,
            f"[sheeprl-tpu watchdog] '{label}' exceeded {self.timeout_s:.1f}s — "
            f"dumping all thread stacks (on_trip={self.on_trip})",
            counter="watchdog_trips",
            span_name="resilience/watchdog_trip",
            category="watchdog",
            args={"label": label, "timeout_s": self.timeout_s, "on_trip": self.on_trip},
        )

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def watch(watchdog: Optional[DispatchWatchdog], label: str):
    """`with watch(runtime.resilience.watchdog, "train_dispatch"):` — the
    zero-cost-when-disabled form train loops use."""
    return nullcontext() if watchdog is None else watchdog.guard(label)


# --------------------------------------------------------------- Resilience
class Resilience:
    """Per-run facade the CLI installs on ``runtime.resilience``.

    Holds the parsed ``resilience`` config section and owns the shared
    :class:`DispatchWatchdog` and :class:`~sheeprl_tpu.core.chaos.ChaosMonkey`.
    ``Runtime`` defaults to :meth:`noop` so bare programmatic use is
    untouched; ``cli.run_algorithm`` swaps in :meth:`from_config`.
    """

    def __init__(self, cfg_section: Optional[Any] = None) -> None:
        self._cfg = cfg_section
        self._watchdog: Optional[DispatchWatchdog] = None
        self._watchdog_built = False
        chaos_cfg = _cfg_get(cfg_section, "chaos", None)
        self.chaos_monkey: Optional[chaos.ChaosMonkey] = None
        if bool(_cfg_get(chaos_cfg, "enabled", False)):
            self.chaos_monkey = chaos.ChaosMonkey(_cfg_get(chaos_cfg, "injectors", []))

    @classmethod
    def noop(cls) -> "Resilience":
        return cls(None)

    @classmethod
    def from_config(cls, cfg: Any) -> "Resilience":
        return cls(_cfg_get(cfg, "resilience", None))

    @property
    def enabled(self) -> bool:
        return self._cfg is not None

    # ------------------------------------------------------------- pieces
    def guard(self, *, rank_zero: bool = True) -> PreemptionGuard:
        """Build + install the loop's PreemptionGuard (noop-shaped when the
        resilience config is absent or preemption handling is off)."""
        preemption = _cfg_get(self._cfg, "preemption", None)
        enabled = bool(_cfg_get(preemption, "enabled", False)) if self._cfg is not None else False
        guard = PreemptionGuard(
            enabled=enabled,
            catch_sigint=bool(_cfg_get(preemption, "catch_sigint", True)),
            write_pointer=bool(_cfg_get(preemption, "write_pointer", True)),
            rank_zero=rank_zero,
            chaos_monkey=self.chaos_monkey,
            on_close=self.close,
        )
        return guard.install()

    @property
    def watchdog(self) -> Optional[DispatchWatchdog]:
        if not self._watchdog_built:
            self._watchdog_built = True
            wd_cfg = _cfg_get(self._cfg, "watchdog", None)
            if self._cfg is not None and bool(_cfg_get(wd_cfg, "enabled", False)):
                self._watchdog = DispatchWatchdog(
                    timeout_s=float(_cfg_get(wd_cfg, "timeout_s", 120.0)),
                    on_trip=str(_cfg_get(wd_cfg, "on_trip", "warn")),
                )
        return self._watchdog

    def supervisor_kwargs(self) -> Optional[Dict[str, Any]]:
        """EnvSupervisor constructor knobs when supervision is enabled, else
        None (how ``make_vector_env`` decides whether to supervise)."""
        sup = _cfg_get(self._cfg, "supervisor", None)
        if self._cfg is None or not bool(_cfg_get(sup, "enabled", False)):
            return None
        return {
            "max_restarts": int(_cfg_get(sup, "max_restarts", 3)),
            "backoff_base_s": float(_cfg_get(sup, "backoff_base_s", 0.05)),
            "backoff_max_s": float(_cfg_get(sup, "backoff_max_s", 5.0)),
            "backoff_jitter": float(_cfg_get(sup, "backoff_jitter", 0.25)),
        }

    def close(self) -> None:
        if self._watchdog is not None:
            self._watchdog.close()
            self._watchdog = None
            self._watchdog_built = False
