"""sheeprl_tpu.core.chaos — first-party fault-injection harness.

Every recovery path in core/resilience.py is only as trustworthy as the
last time it actually ran. This module makes faults a *config input* so the
chaos-marked test suite (and any run with ``resilience.chaos.enabled=True``)
can exercise env-worker crashes, preemption signals, kills mid-save, and
stalled fetches deterministically on CPU.

Two layers:

1. **Fail points** — named, near-zero-cost markers compiled into the hot
   paths that must survive a kill (``utils/checkpoint.py`` brackets each
   phase of an atomic save with :func:`maybe_fail`). Disarmed, the check is
   one module-global bool; armed, the named point raises :class:`ChaosFault`
   exactly where a real crash would land. :func:`maybe_delay` is the latency
   twin used by the blocking-fetch path.

2. **Config-driven injectors** (``cfg.resilience.chaos.injectors``) — a
   list of dicts, each with a ``kind``:

   - ``{"kind": "env_step_raise", "env_rank": 0, "at_step": 7}`` — env
     worker ``env_rank`` raises on its ``at_step``-th ``step()`` call
     (installed as a gym wrapper by ``utils/env.make_vector_env``).
   - ``{"kind": "nan_reward", "env_rank": 0, "at_step": 7}`` — env worker
     ``env_rank`` returns a NaN reward on its ``at_step``-th ``step()``
     call (once). The NaN flows replay buffer → sampled batch → loss →
     grads, which is exactly what the training-health sentinels
     (``telemetry/health.py``) must catch.
   - ``{"kind": "sigterm"|"sigint", "at_step": N}`` — deliver the signal to
     this process once ``policy_step >= N`` (fired from
     ``PreemptionGuard.advance`` so delivery lands at an iteration
     boundary, exactly like a cloud preemption notice).
   - ``{"kind": "kill9", "at_step": N, "replica": K}`` — SIGKILL this
     process: no handler, no drain, no atexit — the ungraceful death a
     supervisor must detect from the outside. ``replica`` (optional)
     targets fleet actor-replica ``K``; without it the injector fires in
     the learner/controller process.
   - ``{"kind": "drop_shipment", "at_step": N, "replica": K, "times": T}``
     — silently swallow the next ``T`` (default 1) rollout shipments on
     the fleet ship path (``fleet.ship`` drop point): the message-loss
     twin of ``kill9``, exercising heartbeat idle-ping liveness rather
     than pipe-EOF death evidence.
   - ``{"kind": "fail_point", "name": "checkpoint.before_commit",
     "at_step": N}`` — arm the named fail point once ``policy_step >= N``
     (``at_step`` 0/absent arms it immediately).
   - ``{"kind": "delayed_fetch", "seconds": 0.2, "at_step": N}`` — arm a
     one-shot sleep inside the blocking action fetch (watchdog food).

Injector firing is recorded in a process-global registry so a restarted env
worker does not re-raise the same injected fault — one configured fault is
one fault. Every fire increments the ``faults_injected`` telemetry counter.

State is process-global on purpose (env thunks are rebuilt by the
supervisor after a crash and must see the same registry); tests call
:func:`reset` around each scenario.
"""

from __future__ import annotations

import os
import signal
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "ChaosFault",
    "ChaosMonkey",
    "arm_drop",
    "arm_fail_point",
    "corrupt_checkpoint",
    "maybe_delay",
    "maybe_drop",
    "maybe_fail",
    "reset",
    "wrap_env_thunks",
]


class ChaosFault(RuntimeError):
    """An injected fault. Deliberately a RuntimeError so production except
    clauses treat it exactly like the organic failure it stands in for."""


# ----------------------------------------------------------- global state
# Fast path: one bool guards every maybe_fail/maybe_delay call site.
_armed: bool = False
_fail_points: Dict[str, int] = {}  # name -> remaining fires (-1 = always)
_delays: Dict[str, float] = {}  # name -> seconds (one-shot)
_drops: Dict[str, int] = {}  # name -> remaining message drops (-1 = always)
_fired: set = set()  # injector ids that already fired (survives env rebuild)


def _count_fault(label: str) -> None:
    try:
        from sheeprl_tpu.telemetry import tracer as tracer_mod

        tracer_mod.current().count("faults_injected")
        tracer_mod.current().count(f"faults_injected/{label}")
    except Exception:  # noqa: BLE001 - telemetry must never mask the fault
        pass


def _refresh_armed() -> None:
    global _armed
    _armed = bool(_fail_points or _delays or _drops)


def arm_fail_point(name: str, times: int = 1) -> None:
    """Arm fail point `name` to raise on its next `times` hits (-1 forever)."""
    _fail_points[name] = int(times)
    _refresh_armed()


def disarm_fail_point(name: str) -> None:
    _fail_points.pop(name, None)
    _refresh_armed()


def arm_delay(name: str, seconds: float) -> None:
    """Arm a one-shot sleep at delay point `name`."""
    _delays[name] = float(seconds)
    _refresh_armed()


def maybe_fail(name: str) -> None:
    """Raise ChaosFault if fail point `name` is armed. Near-free when not."""
    if not _armed:
        return
    remaining = _fail_points.get(name)
    if remaining is None or remaining == 0:
        return
    if remaining > 0:
        _fail_points[name] = remaining - 1
        if _fail_points[name] == 0:
            del _fail_points[name]
        _refresh_armed()
    _count_fault(f"fail_point:{name}")
    raise ChaosFault(f"chaos fail point hit: {name}")


def maybe_delay(name: str) -> None:
    """Sleep once if delay point `name` is armed (then disarm it)."""
    if not _armed:
        return
    seconds = _delays.pop(name, None)
    _refresh_armed()
    if seconds is not None and seconds > 0:
        _count_fault(f"delay:{name}")
        time.sleep(seconds)


def arm_drop(name: str, times: int = 1) -> None:
    """Arm drop point `name` to swallow its next `times` messages (-1 forever)."""
    _drops[name] = int(times)
    _refresh_armed()


def maybe_drop(name: str) -> bool:
    """True if drop point `name` is armed: the caller must silently discard
    the message it was about to send (lost-in-transit, not an error) — the
    injection seam for ``drop_shipment``. Near-free when nothing is armed."""
    if not _armed:
        return False
    remaining = _drops.get(name)
    if remaining is None or remaining == 0:
        return False
    if remaining > 0:
        _drops[name] = remaining - 1
        if _drops[name] == 0:
            del _drops[name]
        _refresh_armed()
    _count_fault(f"drop:{name}")
    return True


def fire_once(injector_id: str, label: str) -> bool:
    """Record `injector_id` as fired; False if it already fired (so a
    supervisor-rebuilt env does not replay the same configured fault)."""
    if injector_id in _fired:
        return False
    _fired.add(injector_id)
    _count_fault(label)
    return True


def reset() -> None:
    """Clear all armed points and the fired registry (test isolation)."""
    _fail_points.clear()
    _delays.clear()
    _drops.clear()
    _fired.clear()
    _refresh_armed()


# --------------------------------------------------------- env injection
class EnvStepChaos:
    """Gym wrapper raising ChaosFault on this env's N-th step() call.

    Kept dependency-free (plain delegation, no gym.Wrapper base) so the
    module imports without gymnasium — only `wrap_env_thunks` needs gym
    environments to exist.
    """

    def __init__(self, env: Any, injector_id: str, at_step: int) -> None:
        self.env = env
        self._injector_id = injector_id
        self._at_step = int(at_step)
        self._n = 0

    def __getattr__(self, name: str) -> Any:
        return getattr(self.env, name)

    def reset(self, **kwargs: Any) -> Any:
        return self.env.reset(**kwargs)

    def step(self, action: Any) -> Any:
        self._n += 1
        if self._n >= self._at_step and fire_once(self._injector_id, "env_step_raise"):
            raise ChaosFault(
                f"injected env-step failure ({self._injector_id}) at local step {self._n}"
            )
        return self.env.step(action)

    def close(self) -> None:
        self.env.close()

    @property
    def unwrapped(self) -> Any:
        return self.env.unwrapped


class EnvRewardChaos:
    """Gym wrapper replacing this env's N-th step() reward with NaN (once).

    The poison propagates the realistic way — replay buffer, sampled batch,
    loss, gradients — so the health sentinels are exercised end to end
    instead of on a hand-planted scalar. Same dependency-free delegation
    shape as :class:`EnvStepChaos`.
    """

    def __init__(self, env: Any, injector_id: str, at_step: int) -> None:
        self.env = env
        self._injector_id = injector_id
        self._at_step = int(at_step)
        self._n = 0

    def __getattr__(self, name: str) -> Any:
        return getattr(self.env, name)

    def reset(self, **kwargs: Any) -> Any:
        return self.env.reset(**kwargs)

    def step(self, action: Any) -> Any:
        obs, reward, terminated, truncated, info = self.env.step(action)
        self._n += 1
        if self._n >= self._at_step and fire_once(self._injector_id, "nan_reward"):
            reward = float("nan")
        return obs, reward, terminated, truncated, info

    def close(self) -> None:
        self.env.close()

    @property
    def unwrapped(self) -> Any:
        return self.env.unwrapped


_ENV_INJECTOR_WRAPPERS = {
    "env_step_raise": EnvStepChaos,
    "nan_reward": EnvRewardChaos,
}


def wrap_env_thunks(
    thunks: List[Callable[[], Any]], injectors: List[Dict[str, Any]], base: int
) -> List[Callable[[], Any]]:
    """Wrap env thunks with the env-side injector wrappers (`env_step_raise`,
    `nan_reward`).

    `base` is the rank's global env offset; injector `env_rank` addresses the
    global env index (matching per-env seed derivation).
    """
    specs: Dict[int, List[Dict[str, Any]]] = {}
    for idx, inj in enumerate(injectors or []):
        kind = str(inj.get("kind"))
        if kind not in _ENV_INJECTOR_WRAPPERS:
            continue
        env_rank = int(inj.get("env_rank", 0))
        specs.setdefault(env_rank, []).append(
            {
                "kind": kind,
                "id": f"{kind}[{idx}]@{env_rank}",
                "at_step": int(inj.get("at_step", 1)),
            }
        )
    if not specs:
        return thunks

    def wrap(thunk: Callable[[], Any], env_specs: List[Dict[str, Any]]) -> Callable[[], Any]:
        def make() -> Any:
            env = thunk()
            for spec in env_specs:
                env = _ENV_INJECTOR_WRAPPERS[spec["kind"]](env, spec["id"], spec["at_step"])
            return env

        return make

    return [
        wrap(t, specs[base + i]) if (base + i) in specs else t
        for i, t in enumerate(thunks)
    ]


# --------------------------------------------------------- step injectors
STEP_INJECTOR_KINDS = (
    "sigterm",
    "sigint",
    "kill9",
    "fail_point",
    "delayed_fetch",
    "drop_shipment",
)


class ChaosMonkey:
    """Policy-step-driven injector driver (signals, fail points, delays).

    Built by ``Resilience.from_config`` and pulsed once per train-loop
    iteration via ``PreemptionGuard.advance(policy_step)``; env_step_raise
    injectors are handled separately by :func:`wrap_env_thunks` because they
    live inside env workers, not the train loop.

    ``replica`` scopes the injector list to one process of a fleet: a spec
    carrying a ``replica`` field fires only in the monkey built with that
    replica index (fleet actor replicas pulse their own monkey per shipped
    step); specs without one fire only in the learner/controller monkey
    (``replica=None``). The fired-once registry is per process, so a
    replica-targeted injector fires once per configured fault even across
    a supervised restart of a *different* replica — but a restarted replica
    process starts with a fresh registry, which is exactly right: the
    supervisor re-delivers the fault only if the spec says so (its
    ``at_step`` gate re-arms against the new process's local step count,
    so `kill9` tests pin `at_step` below the pre-restart step count).
    """

    def __init__(
        self,
        injectors: Optional[List[Dict[str, Any]]],
        replica: Optional[int] = None,
    ) -> None:
        self._injectors: List[Dict[str, Any]] = []
        for idx, inj in enumerate(injectors or []):
            kind = str(inj.get("kind", ""))
            if kind in _ENV_INJECTOR_WRAPPERS:
                continue  # env-side; see wrap_env_thunks
            if kind not in STEP_INJECTOR_KINDS:
                warnings.warn(f"Unknown chaos injector kind {kind!r}: ignored")
                continue
            target = inj.get("replica", None)
            if (target is None) != (replica is None):
                continue  # replica-targeted spec in the learner, or vice versa
            if target is not None and int(target) != int(replica):
                continue
            spec = dict(inj)
            spec["_id"] = f"{kind}[{idx}]"
            spec["_at"] = int(inj.get("at_step", 0) or 0)
            self._injectors.append(spec)

    def on_step(self, policy_step: int) -> None:
        for spec in self._injectors:
            if policy_step < spec["_at"]:
                continue
            if not fire_once(spec["_id"], spec["kind"]):
                continue
            kind = spec["kind"]
            if kind == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            elif kind == "sigint":
                os.kill(os.getpid(), signal.SIGINT)
            elif kind == "kill9":
                # Ungraceful by design: no drain, no final save, no atexit.
                # The fleet supervisor must notice from the outside (pipe
                # EOF / waitpid), exactly like an OOM kill.
                os.kill(os.getpid(), signal.SIGKILL)
            elif kind == "fail_point":
                arm_fail_point(str(spec["name"]), int(spec.get("times", 1)))
            elif kind == "delayed_fetch":
                arm_delay("fetch.harvest", float(spec.get("seconds", 0.1)))
            elif kind == "drop_shipment":
                arm_drop("fleet.ship", int(spec.get("times", 1)))


# --------------------------------------------------- checkpoint corruption
def corrupt_checkpoint(ckpt_path: str, mode: str = "truncate_manifest") -> None:
    """Damage a saved checkpoint in place — the test-side injector for the
    torn-write scenarios `find_latest_valid_checkpoint` must survive.

    Modes: ``truncate_manifest`` (cut the manifest mid-byte, like a kill
    during the metadata write), ``delete_manifest`` (commit never happened —
    pre-atomic-layout directory), ``garbage_manifest`` (bit rot),
    ``delete_arrays`` (payload vanished but manifest survived).
    """
    manifest = os.path.join(ckpt_path, "manifest.json")
    if mode == "truncate_manifest":
        with open(manifest, "rb") as fp:
            blob = fp.read()
        with open(manifest, "wb") as fp:
            fp.write(blob[: max(1, len(blob) // 2)])
    elif mode == "delete_manifest":
        os.remove(manifest)
    elif mode == "garbage_manifest":
        with open(manifest, "wb") as fp:
            fp.write(b"\x00not json\xff")
    elif mode == "delete_arrays":
        import shutil

        for name in os.listdir(ckpt_path):
            full = os.path.join(ckpt_path, name)
            if os.path.isdir(full):
                shutil.rmtree(full)
    else:
        raise ValueError(f"Unknown corruption mode: {mode!r}")
