from sheeprl_tpu.core.mesh import (
    AXIS_NAMES,
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    build_mesh,
    local_batch_size,
    replicate,
    replicated_sharding,
    shard_batch,
)
from sheeprl_tpu.core.precision import Precision, resolve_precision
from sheeprl_tpu.core.prng import KeySequence, make_streams, seed_everything
from sheeprl_tpu.core.runtime import Runtime, get_single_device_runtime

__all__ = [
    "AXIS_NAMES",
    "DATA_AXIS",
    "MODEL_AXIS",
    "batch_sharding",
    "build_mesh",
    "local_batch_size",
    "replicate",
    "replicated_sharding",
    "shard_batch",
    "Precision",
    "resolve_precision",
    "KeySequence",
    "make_streams",
    "seed_everything",
    "Runtime",
    "get_single_device_runtime",
]
