"""The Runtime: the framework's substrate object, replacing Lightning Fabric.

Where the reference passes a ``fabric`` into every algorithm ``main(fabric,
cfg)`` (sheeprl/cli.py:199), this framework passes a :class:`Runtime`. It
owns:

- accelerator/device selection (cpu | tpu | auto),
- multi-host initialization (jax.distributed; DCN between hosts, ICI within),
- the device :class:`~jax.sharding.Mesh` (data × model axes),
- the precision policy,
- seeding and the root PRNG key,
- rank-zero-gated printing/logging helpers.

Unlike Fabric there is no module wrapping / DDP setup: parallelism is sharding
metadata on jitted functions, so "setup_module" has no equivalent — algorithms
jit their train steps with shardings derived from `runtime.mesh`.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import jax
import numpy as np

from sheeprl_tpu.core import mesh as mesh_lib
from sheeprl_tpu.core.precision import Precision, resolve_precision
from sheeprl_tpu.core.prng import seed_everything
from sheeprl_tpu.telemetry import Telemetry

_TPU_PLATFORMS = ("tpu", "axon")


def force_cpu_platform(num_devices: Optional[int] = None, force: bool = False) -> None:
    """Make this process CPU-only (optionally with a virtual n-device CPU
    platform) via the explicit platform dance.

    `jax.devices("cpu")` is not relay-proof: the first backend touch runs
    `xla_bridge.backends()`, which initializes EVERY registered plugin — a
    wedged accelerator relay (e.g. a dead tunnel) then hangs the process
    inside plugin discovery with no timeout, even though only the CPU client
    was asked for. The cure is this dance (clear_backends + config update)
    before anything touches the backend; env-var-only selection does not
    stop the plugin's discovery/connect. This helper is the ONE copy of the
    dance — bench.py, scripts/validate_returns.py and __graft_entry__ all
    call it.

    With ``force=False`` the dance only runs while no backend exists yet:
    once backends are built, clearing them would invalidate every live
    jax.Array in the process (test suites construct many Runtimes
    mid-session), and the accelerator plugin evidently initialized fine
    anyway. ``force=True`` (script entrypoints that own the whole process,
    or a device-count change) clears unconditionally — the caller asserts
    no live arrays it cares about exist.
    """
    global _CPU_PIN_BY_US
    if not force:
        try:
            from jax._src import xla_bridge as _xb

            initialized = bool(_xb._backends)
        except Exception:
            # Private-API drift: fall back to public signals. jax_platforms
            # == "cpu" means the pin already happened (ours or the user's)
            # and the dance is redundant; any OTHER value can be ambient
            # environment (this host's sitecustomize exports
            # JAX_PLATFORMS=axon) and must NOT count as initialized — that
            # would skip the pin and re-expose the wedged-relay hang this
            # function exists to prevent. Otherwise the live-array census:
            # no live arrays -> clearing can invalidate nothing (jit caches
            # re-trace).
            initialized = jax.config.jax_platforms == "cpu" or bool(jax.live_arrays())
        if initialized:
            return
    global _PRE_PIN_JAX_PLATFORMS
    if os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
        # Ours, not the user's: a later TPU launch may undo it — restoring
        # the user's pre-pin value, not deleting it.
        _CPU_PIN_BY_US = True
        _PRE_PIN_JAX_PLATFORMS = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    from jax.extend import backend as _jeb

    _jeb.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    if num_devices is not None and not hasattr(jax.config, "jax_num_cpu_devices"):
        # Older jax has no jax_num_cpu_devices option: the host-platform
        # device count comes only from XLA_FLAGS, which XLA snapshots at the
        # FIRST backend build of the process and never re-reads. Grow the
        # flag preemptively — a `jax.devices()` probe would itself build
        # that first backend and burn the one resize window. Like upstream's
        # test_util, never override a count the environment already names.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={int(num_devices)}".strip()
            )
            _jeb.clear_backends()
    elif num_devices is not None and len(jax.devices()) < int(num_devices):
        # `num_devices` is a MINIMUM, applied only when the environment's
        # own sizing (XLA_FLAGS --xla_force_host_platform_device_count, or
        # a prior jax_num_cpu_devices) comes up short: pinning
        # unconditionally would SHRINK a test harness's 8-device virtual
        # platform to fabric.devices of whichever Runtime launched first.
        _jeb.clear_backends()
        jax.config.update("jax_num_cpu_devices", int(num_devices))


_CPU_PIN_BY_US = False
_PRE_PIN_JAX_PLATFORMS = None


def _unpin_cpu_platform_for_accelerator() -> None:
    """Undo a CPU pin *we* made, so a cpu-launch-then-tpu-launch sequence in
    one process still reaches the accelerator. Only possible while no
    arrays are alive (unpinning rebuilds backends); with live arrays the
    first launch's platform owns the process and the TPU launch fails with
    the ordinary 'no TPU devices visible' error."""
    global _CPU_PIN_BY_US, _PRE_PIN_JAX_PLATFORMS
    if not _CPU_PIN_BY_US or jax.config.jax_platforms != "cpu" or jax.live_arrays():
        return
    if _PRE_PIN_JAX_PLATFORMS is None:
        os.environ.pop("JAX_PLATFORMS", None)
    else:
        os.environ["JAX_PLATFORMS"] = _PRE_PIN_JAX_PLATFORMS
    from jax.extend import backend as _jeb

    _jeb.clear_backends()
    jax.config.update("jax_platforms", _PRE_PIN_JAX_PLATFORMS or "")
    # The pin is undone: reset the bookkeeping so a later force_cpu_platform
    # records the (possibly different) pre-pin value afresh instead of
    # replaying this one.
    _CPU_PIN_BY_US = False
    _PRE_PIN_JAX_PLATFORMS = None


class DispatchThrottle:
    """Bound the number of in-flight async train dispatches.

    XLA dispatch is asynchronous: an off-policy loop with metrics disabled
    and `fabric.player_sync=async` never fetches anything, so the host can
    enqueue train calls (each pinning its sampled device batch — ~13 MB at
    the DreamerV3-S 100K shape) far ahead of the device, growing host
    memory without bound until the client stalls. `add(token)` keeps a
    window of ``depth`` dispatched outputs and blocks on the oldest when
    the window is full — a full window costs no throughput (the device is
    `depth` steps behind at most), an unbounded one took a bench host to
    38 GB RSS before deadlocking.
    """

    def __init__(self, depth: int = 4) -> None:
        from collections import deque

        self._depth = int(depth)
        self._queue = deque()

    def add(self, token: Any) -> None:
        self._queue.append(token)
        while len(self._queue) > self._depth:
            # Deliberate backpressure: blocking on the OLDEST in-flight step is
            # what bounds device queue depth (async dispatch would otherwise
            # run away); the current step keeps riding.
            jax.block_until_ready(self._queue.popleft())  # graftlint: disable=GL002

    def drain(self) -> None:
        while self._queue:
            # End-of-run barrier: draining the pipeline is an explicit sync point.
            jax.block_until_ready(self._queue.popleft())  # graftlint: disable=GL002


def enable_xla_determinism() -> None:
    """Process-wide determinism knob (``cfg.xla_deterministic``).

    Reference semantics: the ``reproducible()`` wrapper
    (sheeprl/cli.py:187-197) sets the CUBLAS workspace config,
    ``cudnn.deterministic`` and ``torch.use_deterministic_algorithms``
    before the entrypoint runs. The XLA analog, applied before the first
    backend touch:

    - **TPU/CPU**: XLA executables are deterministic by construction for a
      fixed program (reductions are compiled tree-reductions, not atomics),
      so the contract here is PRNG discipline — one root key, fold_in-only
      streams (core/prng.py), which ``Runtime.seed_everything`` enforces —
      plus stable compilation inputs (static shapes; no autotune lottery).
    - **GPU** (JAX-on-CUDA completeness): ``--xla_gpu_deterministic_ops``
      forces deterministic reductions/scatters and
      ``--xla_gpu_autotune_level=0`` pins kernel selection. XLA_FLAGS is
      read at backend construction, so this must run before any jax op;
      appended here if absent.
    - ``jax_threefry_partitionable`` makes random bits invariant to
      sharding, so the same seed draws the same values whether a tensor
      lives on 1 or 8 devices — determinism across mesh shapes, not just
      across runs.
    """
    # Drop any pre-existing settings of these two flags (whatever their
    # value — "=false" must not survive a determinism request), then append
    # the deterministic ones.
    kept = [
        tok
        for tok in os.environ.get("XLA_FLAGS", "").split()
        if not tok.startswith(("--xla_gpu_deterministic_ops", "--xla_gpu_autotune_level"))
    ]
    kept += ["--xla_gpu_deterministic_ops=true", "--xla_gpu_autotune_level=0"]
    os.environ["XLA_FLAGS"] = " ".join(kept)
    jax.config.update("jax_threefry_partitionable", True)


def secure_user_cache_dir(subdir: str = "") -> Optional[str]:
    """A per-user 0700 cache directory under XDG, or None if unsecurable.

    Never a world-shared /tmp path: a predictable shared directory would let
    another local user pre-create it and plant poisoned content (CWE-379).
    Created 0700; rejected if it exists but is not owned by us; an existing
    user-owned dir with group/world bits is tightened in place (makedirs'
    mode is umask-subject and not applied to pre-existing dirs). The ONE
    copy of this dance — the XLA compile cache and bench.py's probe marker
    both route through it.
    """
    xdg = os.environ.get("XDG_CACHE_HOME") or os.path.join(os.path.expanduser("~"), ".cache")
    cache_dir = os.path.join(xdg, "sheeprl_tpu", subdir) if subdir else os.path.join(xdg, "sheeprl_tpu")
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        st = os.stat(cache_dir)
        if hasattr(os, "getuid") and st.st_uid != os.getuid():
            return None
        if st.st_mode & 0o077:
            os.chmod(cache_dir, 0o700)
    except OSError:
        return None
    return cache_dir


def user_compilation_cache_dir() -> Optional[str]:
    """Per-user XLA compile-cache path, or None (with a warning) if it
    cannot be secured."""
    import warnings

    cache_dir = secure_user_cache_dir("jax")
    if cache_dir is None:
        warnings.warn(
            "Cannot secure a per-user compile-cache dir; persistent XLA compile cache "
            "DISABLED (every run recompiles). Set XDG_CACHE_HOME or "
            "JAX_COMPILATION_CACHE_DIR to a directory you own."
        )
    return cache_dir


class Runtime:
    def __init__(
        self,
        devices: int | str = 1,
        num_nodes: int = 1,
        strategy: str = "auto",
        accelerator: str = "auto",
        precision: str = "32-true",
        model_axis: int = 1,
        player_device: str = "auto",
        player_sync: str = "fresh",
        shard_superstep: bool = True,
        async_fetch: bool = False,
    ) -> None:
        self.requested_devices = devices
        self.num_nodes = num_nodes
        self.strategy = strategy
        self.accelerator = accelerator
        self.precision: Precision = resolve_precision(precision)
        self.model_axis = int(model_axis)
        # Consumed by PlayerPlacement.resolve via cfg.fabric (core/player.py)
        # and InteractionPipeline.from_config via cfg.fabric (core/interact.py);
        # mirrored here so `instantiate(cfg.fabric)` accepts the keys.
        self.player_device = str(player_device)
        self.player_sync = str(player_sync)
        # Consumed by the fused Anakin lane via cfg.fabric (core/fused_loop.py).
        self.shard_superstep = bool(shard_superstep)
        self.async_fetch = bool(async_fetch)
        self._mesh: Optional[mesh_lib.Mesh] = None
        self._launched = False
        self.seed: Optional[int] = None
        self.root_key: Optional[jax.Array] = None
        # The run's observability surface (sheeprl_tpu/telemetry): the CLI
        # replaces this with Telemetry.from_config(cfg); the default no-op
        # keeps direct Runtime construction (tests, scripts) zero-cost.
        self.telemetry: Telemetry = Telemetry.noop()
        # The run's fault-tolerance surface (sheeprl_tpu/core/resilience):
        # same contract as telemetry — the CLI installs Resilience.from_config
        # and the no-op default keeps bare Runtime construction untouched.
        from sheeprl_tpu.core.resilience import Resilience

        self.resilience: Resilience = Resilience.noop()
        # The run's training-health sentinels (sheeprl_tpu/telemetry/health):
        # the CLI installs HealthMonitor.from_config; the no-op default keeps
        # bare Runtime construction untouched.
        from sheeprl_tpu.telemetry.health import HealthMonitor

        self.health: HealthMonitor = HealthMonitor.noop()

    # ------------------------------------------------------------ lifecycle
    def launch(self) -> "Runtime":
        """Initialize multi-host (if configured) and build the mesh."""
        if self._launched:
            return self
        if self.accelerator == "cpu" or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # A CPU-selected run (fabric.accelerator=cpu, or the user pinned
            # JAX_PLATFORMS=cpu in the environment) must never wait on — or
            # wedge against — an accelerator plugin it will not use. Thread
            # the requested device count through so a multi-device CPU run
            # (fabric.devices=N) gets its virtual N-device platform instead
            # of failing on the default 1-device CPU client.
            n = None
            if self.requested_devices not in ("auto", -1, None):
                n = int(self.requested_devices) * self.model_axis
            force_cpu_platform(num_devices=n)
        elif self.accelerator in _TPU_PLATFORMS:
            _unpin_cpu_platform_for_accelerator()
        if self.num_nodes > 1:
            # On TPU pods jax.distributed.initialize() auto-detects the
            # coordinator from platform metadata; no env var is required.
            # Failure must be loud — silently training per-host with a halved
            # world is worse than crashing. Note: nothing may touch the JAX
            # backend before this call (no jax.devices()/process_count()), so
            # the only tolerated error is "already initialized".
            try:
                jax.distributed.initialize()
            except RuntimeError as e:
                # jax raises "distributed.initialize should only be called
                # once"; older versions said "already initialized".
                msg = str(e).lower()
                if "already" not in msg and "once" not in msg:
                    raise
        # Persistent XLA compilation cache: a fresh process re-lowers every
        # jit closure, and on a remote backend each compile (or even each
        # cache-hit load) pays the link; persisting compiled executables makes
        # restarts and repeated short runs cheap. Opt out by pointing
        # JAX_COMPILATION_CACHE_DIR at "" or your own location.
        if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
            cache_dir = user_compilation_cache_dir()
            if cache_dir is not None:
                jax.config.update("jax_compilation_cache_dir", cache_dir)
            if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        self._mesh = mesh_lib.build_mesh(
            devices=self._select_devices(),
            data_axis_size=None,
            model_axis_size=self.model_axis,
        )
        self._launched = True
        return self

    def _select_devices(self) -> Sequence[jax.Device]:
        if self.accelerator == "cpu":
            devs = jax.devices("cpu")
        elif self.accelerator in _TPU_PLATFORMS:
            devs = [d for d in jax.devices() if d.platform in _TPU_PLATFORMS]
            if not devs:
                raise RuntimeError("accelerator=tpu requested but no TPU devices are visible")
        else:  # auto
            devs = jax.devices()
        if self.requested_devices in ("auto", -1, None):
            return devs
        n = int(self.requested_devices) * self.model_axis
        if n > len(devs):
            raise RuntimeError(
                f"Requested {n} devices (devices={self.requested_devices} x model_axis={self.model_axis}) "
                f"but only {len(devs)} are visible"
            )
        return devs[:n]

    # ------------------------------------------------------------ properties
    @property
    def mesh(self) -> mesh_lib.Mesh:
        if self._mesh is None:
            self.launch()
        return self._mesh

    @property
    def device(self) -> jax.Device:
        return self.mesh.devices.flat[0]

    @property
    def world_size(self) -> int:
        """Number of data-parallel workers (devices on the data axis).

        Plays the role of the reference's world_size: per_rank_* config values
        are per data-parallel shard.
        """
        return int(self.mesh.shape[mesh_lib.DATA_AXIS])

    @property
    def global_rank(self) -> int:
        return jax.process_index()

    @property
    def node_rank(self) -> int:
        return jax.process_index()

    @property
    def is_global_zero(self) -> bool:
        return jax.process_index() == 0

    # ------------------------------------------------------------ utilities
    def seed_everything(self, seed: int) -> jax.Array:
        # Different hosts must draw different env seeds but identical model
        # init: algorithms use root_key (identical) for params and
        # fold_in(rank) streams for env/sampling.
        self.seed = seed
        # Post-launch the backend exists, so the rank is known here — pass it
        # rather than having seed_everything re-probe via private API.
        rank = jax.process_index() if self._launched else None
        self.root_key = seed_everything(seed, rank=rank)
        return self.root_key

    def print(self, *args: Any, **kwargs: Any) -> None:
        if self.is_global_zero:
            print(*args, **kwargs)

    def shard_batch(self, tree: Any, axis: int = 0) -> Any:
        return mesh_lib.shard_batch(tree, self.mesh, axis=axis)

    def replicate(self, tree: Any) -> Any:
        return mesh_lib.replicate(tree, self.mesh)

    def host_init(self):
        """Context manager: run eager parameter/optimizer initialization on
        the host CPU backend.

        Flax ``.init`` and optax ``.init`` dispatch eagerly, one primitive at
        a time; on a remote accelerator every one of those dispatches pays the
        link round trip (minutes for a Dreamer-sized agent behind a tunneled
        chip, microseconds on the host). Initialize host-side, then move the
        finished pytrees to the mesh in one pass with :meth:`shard_params`
        (host-to-device transfers are bulk and cheap).
        """
        return jax.default_device(jax.devices("cpu")[0])

    def shard_params(self, tree: Any, min_dim: int = 1024) -> Any:
        """Place params/opt-state on the mesh: wide leaves tensor-parallel over
        the `model` axis (when model_axis > 1), the rest replicated."""
        return mesh_lib.shard_wide_params(tree, self.mesh, min_dim=min_dim)

    def to_host(self, tree: Any) -> Any:
        return jax.tree_util.tree_map(np.asarray, tree)

    def local_batch_size(self, global_batch: int) -> int:
        return mesh_lib.local_batch_size(global_batch, self.mesh)

    def __repr__(self) -> str:  # pragma: no cover
        # repr must not initialize the JAX backend as a side effect (that
        # would lock in the platform before launch()).
        if self._mesh is None:
            return f"Runtime(accelerator={self.accelerator}, precision={self.precision.name}, unlaunched)"
        return (
            f"Runtime(accelerator={self.accelerator}, precision={self.precision.name}, "
            f"mesh={dict(self.mesh.shape)}, processes={jax.process_count()})"
        )


def get_single_device_runtime(runtime: Runtime) -> Runtime:
    """A single-device view of an existing runtime, for the *player*.

    Parity with `get_single_device_fabric` (sheeprl/utils/fabric.py:8-35): env
    interaction must never synchronize across the mesh. In JAX terms the
    player just runs jitted forwards on device 0 with replicated params — no
    collective ops are traced, so a separate strategy object is unnecessary;
    this helper exists to make that intent explicit at call sites.
    """
    view = Runtime(
        devices=1,
        num_nodes=1,
        strategy="single_device",
        accelerator=runtime.accelerator,
        precision=runtime.precision.name,
        model_axis=1,
    )
    # The player must live on a device *this process* can address: the global
    # mesh's first device belongs to process 0, which is remote on other hosts.
    local = [d for d in runtime.mesh.devices.flat if d.process_index == jax.process_index()]
    player_device = local[0] if local else jax.local_devices()[0]
    view._mesh = mesh_lib.build_mesh(devices=[player_device], model_axis_size=1)
    view._launched = True
    view.seed = runtime.seed
    view.root_key = runtime.root_key
    view.telemetry = runtime.telemetry
    view.resilience = runtime.resilience
    view.health = runtime.health
    return view
