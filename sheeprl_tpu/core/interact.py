"""Pipelined environment interaction: async action fetch, env-slice software
pipelining, and double-buffered obs staging.

Every train loop in this repo has the same serial hot path per env step::

    np_obs = prepare_obs(obs)            # host: allocate + cast/stack
    out    = player_fn(params, np_obs)   # device: dispatch (async)
    acts   = jax.device_get(out)         # host BLOCKS until inference + D2H done
    envs.step(acts)                      # host: device idle the whole time

The device->host copy and the env step are strictly serialized even though
neither needs the other's resources. This module breaks that false dependency
three ways, all behind config flags that default to the exact serial behavior:

1. **Async action fetch** (``fabric.async_fetch``): the D2H copy is *started*
   at dispatch time via ``jax.Array.copy_to_host_async()`` and *harvested*
   (one ``jax.device_get``, now mostly a wait-free memcpy) just before
   ``envs.step`` — so the copy rides under whatever host work sits between
   dispatch and use (buffer writes, fused-train dispatch).
2. **Env-slice software pipelining** (``env.pipeline_slices``): the E env
   columns are split into S independent vector envs (:class:`EnvSliceGroup`);
   :meth:`InteractionPipeline.interact` dispatches the policy per slice and
   then steps slice k on the host while slice k+1's actions are still in
   flight on the device. Recurrent player state and PRNG keys are kept
   per-slice; SAME_STEP autoreset bookkeeping (``final_info`` masks,
   ``final_obs`` object arrays) is merged back to the full-E layout so loops
   are oblivious to the slicing.
3. **Double-buffered obs staging** (:class:`ObsStager`): ``prepare_obs``
   writes into two preallocated host buffers in alternation instead of
   allocating per step. Two buffers, not one, because the previous step's
   staged obs may still back an in-flight host->device transfer.

``pipeline_slices=1`` with async fetch off reduces to exactly the serial
loop — same op order, same PRNG folds, bit-identical rollouts (the
equivalence tests in ``tests/test_core/test_interact.py`` pin this).

gymnasium's ``SyncVectorEnv`` REUSES its observation/reward buffers across
steps, so :meth:`InteractionPipeline.interact` (which steps envs before the
caller's replay-buffer writes) returns obs copied into pipeline-owned
ping-pong buffers — the obs a loop holds stays valid for one full iteration
regardless of what the vector env does underneath.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import gymnasium as gym
import numpy as np

from sheeprl_tpu.telemetry import trace_context
from sheeprl_tpu.telemetry import tracer as tracer_mod

_MISSING = object()

OVERLAP_GAUGE = "interaction_overlap_fraction"
ASYNC_BYTES_COUNTER = "async_fetch_bytes"
BLOCKING_CALLS_COUNTER = "blocking_fetch_calls"


# --------------------------------------------------------------------- trees
def split_ranges(num_envs: int, slices: int) -> List[Tuple[int, int]]:
    """Partition ``num_envs`` columns into ``slices`` contiguous ranges
    (first ``num_envs % slices`` ranges get one extra column, matching
    ``np.array_split``)."""
    if slices < 1:
        raise ValueError(f"pipeline_slices must be >= 1, got {slices}")
    if slices > num_envs:
        raise ValueError(f"pipeline_slices ({slices}) cannot exceed num_envs ({num_envs})")
    base, extra = divmod(num_envs, slices)
    ranges: List[Tuple[int, int]] = []
    start = 0
    for k in range(slices):
        stop = start + base + (1 if k < extra else 0)
        ranges.append((start, stop))
        start = stop
    return ranges


def tree_slice(tree: Any, start: int, stop: int) -> Any:
    """Slice axis 0 of every array leaf of a (possibly dict) obs tree."""
    if isinstance(tree, dict):
        return {k: tree_slice(v, start, stop) for k, v in tree.items()}
    return tree[start:stop]


def tree_concat(parts: Sequence[Any]) -> Any:
    """Concatenate per-slice obs/output trees back to the full-E layout."""
    first = parts[0]
    if isinstance(first, dict):
        return {k: tree_concat([p[k] for p in parts]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(tree_concat([p[i] for p in parts]) for i in range(len(first)))
    return np.concatenate([np.asarray(p) for p in parts], axis=0)


def _zeros_like_rows(template: np.ndarray, n: int) -> np.ndarray:
    if template.dtype == object:
        return np.full((n,) + template.shape[1:], None, dtype=object)
    return np.zeros((n,) + template.shape[1:], dtype=template.dtype)


def merge_infos(infos: Sequence[Dict[str, Any]], counts: Sequence[int]) -> Dict[str, Any]:
    """Merge per-slice vector-env infos back to the full-E layout.

    gymnasium's SAME_STEP autoreset info protocol is per-env arrays plus
    ``_``-prefixed boolean presence masks, nested dicts (``final_info`` →
    ``episode``), and object arrays (``final_obs``). A slice in which no env
    finished simply lacks the key, so absent entries are filled with zeros
    (False for masks, None for object arrays) — exactly what one big vector
    env would have produced for those columns."""
    keys: List[str] = []
    for inf in infos:
        for k in inf:
            if k not in keys:
                keys.append(k)
    merged: Dict[str, Any] = {}
    for key in keys:
        vals = [inf.get(key, _MISSING) for inf in infos]
        merged[key] = _merge_info_value(vals, counts)
    return merged


def _merge_info_value(vals: Sequence[Any], counts: Sequence[int]) -> Any:
    template = None
    template_count = 0
    for v, n in zip(vals, counts):
        if v is not _MISSING:
            template, template_count = v, n
            break
    if isinstance(template, dict):
        return merge_infos([v if isinstance(v, dict) else {} for v in vals], counts)
    if isinstance(template, np.ndarray) and template.ndim >= 1 and len(template) == template_count:
        parts = [
            _zeros_like_rows(template, n) if v is _MISSING else np.asarray(v)
            for v, n in zip(vals, counts)
        ]
        return np.concatenate(parts, axis=0)
    # Scalar / non-per-env payload: first present value wins.
    return template


# ------------------------------------------------------------ EnvSliceGroup
class EnvSliceGroup(gym.vector.VectorEnv):
    """S independent vector envs presented as one ``num_envs``-wide vector env.

    Built by :func:`sheeprl_tpu.utils.env.make_vector_env` when
    ``env.pipeline_slices > 1``. The combined :meth:`step`/:meth:`reset`
    surface is drop-in for every loop (slices step sequentially, same per-env
    call order as one big ``SyncVectorEnv``, so env RNG streams match); the
    per-slice :meth:`step_slice` is what
    :meth:`InteractionPipeline.interact` pipelines against.

    Seeding matches the monolithic env: ``reset(seed=s)`` seeds slice k with
    ``s + start_k``, and gymnasium's vector reset adds the within-slice index,
    so global env j always sees ``s + j``."""

    def __init__(self, envs: Sequence[gym.vector.VectorEnv]) -> None:
        if not envs:
            raise ValueError("EnvSliceGroup needs at least one sub vector env")
        self.envs: List[gym.vector.VectorEnv] = list(envs)
        counts = [e.num_envs for e in self.envs]
        self.num_envs = int(sum(counts))
        self.slice_ranges = []
        start = 0
        for n in counts:
            self.slice_ranges.append((start, start + n))
            start += n
        first = self.envs[0]
        self.single_observation_space = first.single_observation_space
        self.single_action_space = first.single_action_space
        self.observation_space = gym.vector.utils.batch_space(
            self.single_observation_space, self.num_envs
        )
        self.action_space = gym.vector.utils.batch_space(self.single_action_space, self.num_envs)
        self.metadata = first.metadata
        self.render_mode = getattr(first, "render_mode", None)
        self.spec = getattr(first, "spec", None)

    @property
    def slices(self) -> int:
        return len(self.envs)

    @property
    def slice_counts(self) -> List[int]:
        return [s1 - s0 for s0, s1 in self.slice_ranges]

    def reset(
        self, *, seed: Optional[Any] = None, options: Optional[dict] = None
    ) -> Tuple[Any, Dict[str, Any]]:
        obs_parts: List[Any] = []
        info_parts: List[Dict[str, Any]] = []
        for (s0, s1), env in zip(self.slice_ranges, self.envs):
            if isinstance(seed, int):
                sub_seed: Optional[Any] = seed + s0
            elif isinstance(seed, (list, tuple)):
                sub_seed = list(seed[s0:s1])
            else:
                sub_seed = seed
            obs, info = env.reset(seed=sub_seed, options=options)
            obs_parts.append(obs)
            info_parts.append(info)
        return tree_concat(obs_parts), merge_infos(info_parts, self.slice_counts)

    def step_slice(self, k: int, actions: Any) -> Tuple[Any, Any, Any, Any, Dict[str, Any]]:
        """Step ONLY slice k (actions in slice-local layout)."""
        return self.envs[k].step(actions)

    def step(self, actions: Any) -> Tuple[Any, Any, Any, Any, Dict[str, Any]]:
        results = []
        for k, (s0, s1) in enumerate(self.slice_ranges):
            results.append(self.step_slice(k, tree_slice(actions, s0, s1)))
        return self.merge_step(results)

    def merge_step(
        self, results: Sequence[Tuple[Any, Any, Any, Any, Dict[str, Any]]]
    ) -> Tuple[Any, Any, Any, Any, Dict[str, Any]]:
        counts = self.slice_counts
        obs = tree_concat([r[0] for r in results])
        rewards = np.concatenate([np.asarray(r[1]) for r in results], axis=0)
        terminated = np.concatenate([np.asarray(r[2]) for r in results], axis=0)
        truncated = np.concatenate([np.asarray(r[3]) for r in results], axis=0)
        infos = merge_infos([r[4] for r in results], counts)
        return obs, rewards, terminated, truncated, infos

    def call(self, name: str, *args: Any, **kwargs: Any) -> tuple:
        out: List[Any] = []
        for env in self.envs:
            out.extend(env.call(name, *args, **kwargs))
        return tuple(out)

    def close(self, **kwargs: Any) -> None:
        for env in self.envs:
            env.close(**kwargs)


# ---------------------------------------------------------------- ObsStager
class ObsStager:
    """Double-buffered ``prepare_obs`` staging.

    Wraps a ``prepare(obs, out=None) -> host tree`` callable. The first two
    calls allocate (as today); afterwards the two result trees are reused in
    alternation via the ``out=`` parameter, so steady-state staging performs
    zero allocations. Two buffers because buffer t-1 may still back an
    in-flight host->device transfer when step t stages."""

    __slots__ = ("_prepare", "_buffers", "_idx")

    def __init__(self, prepare: Callable[..., Any]) -> None:
        self._prepare = prepare
        self._buffers: List[Any] = [None, None]
        self._idx = 0

    def __call__(self, obs: Any) -> Any:
        self._idx ^= 1
        out = self._prepare(obs, out=self._buffers[self._idx])
        self._buffers[self._idx] = out
        return out


# -------------------------------------------------------------------- stats
class FetchStats:
    """Per-run interaction accounting (one instance per pipeline)."""

    __slots__ = (
        "steps",
        "async_fetches",
        "blocking_fetches",
        "async_fetch_bytes",
        "fetch_blocked_s",
        "fetch_ride_s",
        "policy_dispatch_s",
        "env_step_s",
    )

    def __init__(self) -> None:
        self.steps = 0
        self.async_fetches = 0
        self.blocking_fetches = 0
        self.async_fetch_bytes = 0
        self.fetch_blocked_s = 0.0
        self.fetch_ride_s = 0.0
        self.policy_dispatch_s = 0.0
        self.env_step_s = 0.0

    @property
    def overlap_fraction(self) -> float:
        """Fraction of total fetch latency hidden under other host work:
        ride / (ride + blocked). 0.0 when fully serial, -> 1.0 when every
        copy finishes before its harvest."""
        total = self.fetch_ride_s + self.fetch_blocked_s
        return self.fetch_ride_s / total if total > 0 else 0.0

    def as_dict(self) -> Dict[str, float]:
        return {
            "steps": self.steps,
            "async_fetches": self.async_fetches,
            "blocking_fetches": self.blocking_fetches,
            "async_fetch_bytes": self.async_fetch_bytes,
            "fetch_blocked_s": self.fetch_blocked_s,
            "fetch_ride_s": self.fetch_ride_s,
            "policy_dispatch_s": self.policy_dispatch_s,
            "env_step_s": self.env_step_s,
            "overlap_fraction": self.overlap_fraction,
        }


# bench.py reads the slot from its own thread while a decoupled trainer
# may still be publishing; swap under the lock.
_stats_lock = threading.Lock()
_LAST_RUN_STATS: Optional[Dict[str, float]] = None  # graftlint: guarded-by(_stats_lock)


def last_run_stats() -> Optional[Dict[str, float]]:
    """The stats dict from the most recent :meth:`InteractionPipeline.publish`
    in this process — how ``bench.py`` reads a leg's interaction time split
    without parsing logs."""
    with _stats_lock:
        return _LAST_RUN_STATS


# ------------------------------------------------------------- PendingFetch
class PendingFetch:
    """Handle for one device->host action fetch.

    Created at dispatch time by :meth:`InteractionPipeline.fetch`; in async
    mode the D2H copy of every ``jax.Array`` leaf is started immediately
    (``copy_to_host_async``). :meth:`harvest` performs the one blocking
    ``jax.device_get`` and books the time split: submit→harvest is *ride*
    (hidden under host work), the ``device_get`` duration is *blocked*."""

    __slots__ = ("_pipeline", "_tree", "_label", "_async", "_submit_t", "_result", "_done", "_ctx")

    def __init__(self, pipeline: "InteractionPipeline", tree: Any, label: str) -> None:
        self._pipeline = pipeline
        self._tree = tree
        self._label = label
        self._async = pipeline.async_fetch
        self._result: Any = None
        self._done = False
        # Captured at dispatch: the harvest may happen an iteration later
        # (or after other work), but the fetch span belongs causally to the
        # iteration that dispatched it.
        parent = trace_context.current()
        self._ctx = parent.child() if parent is not None else None
        if self._async:
            import jax

            nbytes = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                start = getattr(leaf, "copy_to_host_async", None)
                if start is not None:
                    start()
                    nbytes += int(getattr(leaf, "nbytes", 0))
            stats = pipeline.stats
            stats.async_fetches += 1
            stats.async_fetch_bytes += nbytes
            tracer = tracer_mod.current()
            tracer.count(ASYNC_BYTES_COUNTER, nbytes)
        self._submit_t = time.perf_counter()

    def harvest(self) -> Any:
        """Blocking ``device_get``; idempotent (later calls return the cached
        host tree)."""
        if self._done:
            return self._result
        import jax

        from sheeprl_tpu.core import chaos

        t0 = time.perf_counter()
        watchdog = self._pipeline.watchdog
        if watchdog is not None:
            with watchdog.guard(f"fetch/{self._label}"):
                # Inside the armed window: a delayed_fetch drill must look
                # exactly like a hung device fetch to the watchdog.
                chaos.maybe_delay("fetch.harvest")
                out = jax.device_get(self._tree)
        else:
            chaos.maybe_delay("fetch.harvest")
            out = jax.device_get(self._tree)
        t1 = time.perf_counter()
        stats = self._pipeline.stats
        stats.fetch_blocked_s += t1 - t0
        tracer = tracer_mod.current()
        if self._async:
            stats.fetch_ride_s += t0 - self._submit_t
        else:
            stats.blocking_fetches += 1
            tracer.count(BLOCKING_CALLS_COUNTER, 1)
        if tracer.enabled:
            nbytes = tracer_mod.tree_bytes(out)
            tracer.add_span(
                f"fetch/{self._label}",
                "fetch",
                t0,
                t1 - t0,
                {"bytes": nbytes, "async": self._async},
                ctx=self._ctx,
            )
            tracer.count("device_get_calls", 1)
            tracer.count("device_get_bytes", nbytes)
        self._result = out
        self._done = True
        self._tree = None
        return out


class InteractionResult(NamedTuple):
    outputs: Any
    obs: Any
    rewards: np.ndarray
    terminated: np.ndarray
    truncated: np.ndarray
    infos: Dict[str, Any]


# ------------------------------------------------------- InteractionPipeline
class InteractionPipeline:
    """Orchestrates the per-step interaction of one train loop.

    Two usage levels:

    - **fetch-only** (every loop): replace ``telemetry.fetch(tree)`` with
      ``pending = pipeline.fetch(tree)`` at dispatch time and
      ``pending.harvest()`` at first use. With ``async_fetch`` off this is
      op-for-op the old blocking fetch (just moved to the use site, which
      changes no numerics); with it on, the copy rides under the host work
      in between.
    - **full interact()** (dreamer_v3 / sac / ppo): the pipeline owns the
      slice loop — obs slicing + staging, per-slice policy dispatch, async
      fetch, per-slice env stepping, and the merge back to full-E layout —
      plus per-slice recurrent state (:meth:`init_state` / :meth:`map_state`)
      and per-slice PRNG keys (:meth:`set_key`).

    At ``slices == 1`` and async off, :meth:`interact` performs exactly
    prepare → dispatch → device_get → envs.step with the loop's own key
    passed through untouched: bit-identical to the serial loop.
    """

    def __init__(
        self,
        num_envs: int,
        *,
        slices: int = 1,
        async_fetch: bool = False,
        name: str = "interaction",
    ) -> None:
        self.num_envs = int(num_envs)
        self.slices = int(slices)
        self.async_fetch = bool(async_fetch)
        self.name = name
        self._ranges = split_ranges(self.num_envs, self.slices)
        self.stats = FetchStats()
        # Optional DispatchWatchdog (core/resilience.py) armed around every
        # blocking harvest; loops install it right after construction.
        self.watchdog: Optional[Any] = None
        self._states: Optional[List[Any]] = None
        self._keys: Optional[List[Any]] = None
        self._stagers: Dict[int, ObsStager] = {}
        self._obs_bufs: List[Any] = [None, None]
        self._obs_idx = 0

    @classmethod
    def from_config(
        cls, cfg: Any, num_envs: Optional[int] = None, name: str = "interaction"
    ) -> "InteractionPipeline":
        """Build from the composed run config: ``env.pipeline_slices`` and
        ``fabric.async_fetch`` (both optional, defaulting to the serial
        behavior)."""
        n = int(num_envs if num_envs is not None else cfg.env.num_envs)
        slices = int(cfg.env.get("pipeline_slices", 1) or 1)
        async_fetch = bool(cfg.fabric.get("async_fetch", False))
        return cls(n, slices=slices, async_fetch=async_fetch)

    # ------------------------------------------------------------ fetch-only
    def fetch(self, tree: Any, label: str = "player_actions") -> PendingFetch:
        """Submit a device->host fetch NOW (async copy if enabled); call
        ``.harvest()`` on the returned handle where the host values are
        first needed."""
        return PendingFetch(self, tree, label)

    @property
    def overlap_train(self) -> bool:
        """Whether a loop should dispatch its (fused) train step between
        fetch submit and harvest. Pure host-side reordering — train then
        sees replay data through step t-1 instead of t, the documented
        one-step staleness relaxation — so it is only worth doing when the
        fetch is actually async."""
        return self.async_fetch

    # ---------------------------------------------------------- slice state
    @property
    def slice_ranges(self) -> List[Tuple[int, int]]:
        return list(self._ranges)

    def init_state(self, fn: Callable[[int, Tuple[int, int]], Any]) -> None:
        """Initialize per-slice recurrent player state:
        ``fn(n_envs_in_slice, (start, stop)) -> state``."""
        self._states = [fn(s1 - s0, (s0, s1)) for s0, s1 in self._ranges]

    def map_state(self, fn: Callable[[Any, Tuple[int, int]], Any]) -> None:
        """Transform every slice's state (e.g. masked reset on done envs):
        ``fn(state, (start, stop)) -> new state``. The mask the caller closes
        over is in GLOBAL env coordinates; ``(start, stop)`` selects the
        slice's columns."""
        if self._states is None:
            raise RuntimeError("init_state() was never called")
        self._states = [fn(s, rng) for s, rng in zip(self._states, self._ranges)]

    @property
    def states(self) -> Optional[List[Any]]:
        return self._states

    def set_key(self, key: Any) -> None:
        """Hand the rollout PRNG key to the pipeline. At ``slices == 1`` the
        key passes through the policy untouched (exact serial semantics); at
        S > 1 it is split once into S independent per-slice streams."""
        if self.slices == 1:
            self._keys = [key]
        else:
            import jax

            self._keys = list(jax.random.split(key, self.slices))

    @property
    def key(self) -> Any:
        """The (first) rollout key — for checkpointing at ``slices == 1``."""
        return self._keys[0] if self._keys else None

    # ------------------------------------------------------------- interact
    def _stager(self, k: int, prepare: Callable[..., Any]) -> ObsStager:
        st = self._stagers.get(k)
        if st is None:
            st = ObsStager(prepare)
            self._stagers[k] = st
        return st

    def stash_obs(self, obs: Any) -> Any:
        """Copy merged next-obs into pipeline-owned ping-pong buffers.
        gymnasium vector envs reuse their observation buffer across steps;
        the copy makes the obs a loop holds valid for a full iteration.
        :meth:`interact` stashes automatically; loops with a non-pipelined
        branch (off-policy prefill steps env directly with random actions)
        call this on that branch's obs so the two paths stay aliasing-safe
        with each other."""

        def _copy_into(buf: Any, src: Any) -> Any:
            if isinstance(src, dict):
                if not isinstance(buf, dict):
                    return {k: _copy_into(None, v) for k, v in src.items()}
                return {k: _copy_into(buf.get(k), v) for k, v in src.items()}
            src_arr = np.asarray(src)
            if (
                isinstance(buf, np.ndarray)
                and buf.shape == src_arr.shape
                and buf.dtype == src_arr.dtype
            ):
                np.copyto(buf, src_arr)
                return buf
            return src_arr.copy()

        self._obs_idx ^= 1
        out = _copy_into(self._obs_bufs[self._obs_idx], obs)
        self._obs_bufs[self._obs_idx] = out
        return out

    def interact(
        self,
        envs: gym.vector.VectorEnv,
        obs: Any,
        policy: Callable[[Any, Any, Any], Tuple[Any, Any, Any]],
        *,
        prepare: Optional[Callable[..., Any]] = None,
        to_env_actions: Optional[Callable[[Any, int], Any]] = None,
        before_harvest: Optional[Callable[[], None]] = None,
        label: str = "player_actions",
    ) -> InteractionResult:
        """One full pipelined env step.

        ``policy(np_obs, state, key) -> (fetch_tree, new_state, new_key)`` is
        called once per slice (state/key are ``None`` when unused);
        ``prepare(obs_slice, out=None)`` stages the raw obs slice (double
        buffered per slice); ``to_env_actions(host_outputs, n_envs)`` maps
        the harvested host tree to the env action array.

        ``before_harvest`` runs after every slice's policy has been
        dispatched and its fetch submitted, but before the first harvest —
        the slot where off-policy loops dispatch their fused train step so
        train compute overlaps the action copy and the host env step.

        Dispatch order: every slice's policy is dispatched and its fetch
        submitted first (device queue is deep, dispatch is cheap), then
        slices are harvested and stepped in order — slice k steps on the
        host while slice k+1's copy is still in flight.
        """
        S = self.slices
        tracer = tracer_mod.current()
        use_slices = S > 1
        if use_slices and not (isinstance(envs, EnvSliceGroup) and envs.slices == S):
            raise ValueError(
                f"pipeline_slices={S} requires an EnvSliceGroup with {S} slices "
                "(build envs through make_vector_env)"
            )
        pendings: List[PendingFetch] = []
        t_dispatch = time.perf_counter()
        for k, (s0, s1) in enumerate(self._ranges):
            obs_k = obs if not use_slices else tree_slice(obs, s0, s1)
            np_obs = self._stager(k, prepare)(obs_k) if prepare is not None else obs_k
            state_k = self._states[k] if self._states is not None else None
            key_k = self._keys[k] if self._keys is not None else None
            with tracer.span(f"{self.name}/dispatch/slice{k}", "interaction"):
                fetch_tree, new_state, new_key = policy(np_obs, state_k, key_k)
            if self._states is not None:
                self._states[k] = new_state
            if self._keys is not None:
                self._keys[k] = new_key
            pendings.append(self.fetch(fetch_tree, label=label))
        self.stats.policy_dispatch_s += time.perf_counter() - t_dispatch
        if before_harvest is not None:
            before_harvest()
        outputs_parts: List[Any] = []
        step_parts: List[Tuple[Any, Any, Any, Any, Dict[str, Any]]] = []
        for k, (s0, s1) in enumerate(self._ranges):
            host = pendings[k].harvest()
            outputs_parts.append(host)
            acts = to_env_actions(host, s1 - s0) if to_env_actions is not None else host
            t0 = time.perf_counter()
            with tracer.span(f"{self.name}/env_step/slice{k}", "interaction"):
                if use_slices:
                    step_parts.append(envs.step_slice(k, acts))
                else:
                    step_parts.append(envs.step(acts))
            self.stats.env_step_s += time.perf_counter() - t0
        self.stats.steps += 1
        if use_slices:
            outputs = tree_concat(outputs_parts)
            next_obs, rewards, terminated, truncated, infos = envs.merge_step(step_parts)
        else:
            outputs = outputs_parts[0]
            next_obs, rewards, terminated, truncated, infos = step_parts[0]
        next_obs = self.stash_obs(next_obs)
        if self.stats.steps % 128 == 0:
            tracer.set_gauge(OVERLAP_GAUGE, self.stats.overlap_fraction)
        return InteractionResult(outputs, next_obs, rewards, terminated, truncated, infos)

    # -------------------------------------------------------------- publish
    def snapshot(self) -> Dict[str, float]:
        return self.stats.as_dict()

    def publish(self) -> Dict[str, float]:
        """End-of-run: publish the stats dict to the module-level
        :func:`last_run_stats` slot (read in-process by ``bench.py``) and the
        overlap-fraction gauge to the current tracer."""
        global _LAST_RUN_STATS
        stats = self.snapshot()
        with _stats_lock:
            _LAST_RUN_STATS = stats
        tracer_mod.current().set_gauge(OVERLAP_GAUGE, stats["overlap_fraction"])
        return stats
