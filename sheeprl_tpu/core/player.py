"""Latency-aware placement of the per-env-step player.

Training on the mesh is throughput-bound: big batched matmuls that want the
MXU. The per-env-step policy forward is the opposite regime — a tiny
computation whose wall-clock cost is dominated by dispatch + fetch latency
between the host (where the env lives) and the accelerator. On a directly
attached chip that latency is ~100 us and the mesh device wins. Behind a
remote/tunneled chip it can exceed 100 ms per call, turning a microsecond
matmul into a 10 Hz interaction loop while the chip idles.

This module makes the placement explicit and configurable
(``fabric.player_device``):

- ``mesh``  — player runs on the first mesh device (classic coupled layout;
  the analog of the reference's single-device player fabric,
  sheeprl/utils/fabric.py:8-35).
- ``host``  — player runs on the host CPU backend; a :class:`ParamMirror`
  keeps a copy of the training parameters on the host, refreshed after every
  optimizer step (the analog of the reference's decoupled mode, where the
  trainer broadcasts a flattened parameter vector back to the player,
  sheeprl/algos/sac/sac_decoupled.py:260-263 — here it is a device-to-host
  array copy, no flatten/unflatten dance).
- ``auto``  — measure the mesh dispatch latency once and pick ``host`` when
  the round trip is slower than :data:`AUTO_LATENCY_THRESHOLD_S` (and the
  player parameters are small enough for the copy to be cheap).

Parameter-sync semantics (``fabric.player_sync``):

- ``fresh`` — the mirror copy is enqueued immediately after each update and
  the player's next step waits for it: the player always acts with the
  current weights, matching the reference's coupled tied-weights behavior.
- ``async`` — the copy is enqueued but never waited on; the player keeps
  acting with the newest snapshot that has *finished* transferring. Under
  link backpressure intermediate snapshots are skipped (newest wins), so the
  interaction loop never blocks on the weight link. On-policy algorithms
  (PPO/A2C) ignore this setting: their update happens between rollouts, and
  correctness requires the rollout to run on the post-update weights.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from sheeprl_tpu.telemetry import tracer as tracer_mod

AUTO_LATENCY_THRESHOLD_S = 2e-3
# Above this the host copy of the player parameters costs more than the
# dispatch latency it saves (and compiles slowly on CPU): stay on the mesh.
AUTO_MAX_PARAM_BYTES = 64 * 1024 * 1024
# How long an `auto` placement trusts its latency probe before re-measuring.
# A tunnel that degrades (or heals) MID-RUN — the observed failure mode of a
# relayed chip — would otherwise keep the stale placement until restart.
AUTO_REPROBE_TTL_S = float(os.environ.get("SHEEPRL_PLAYER_REPROBE_TTL_S", "300"))

_latency_cache: dict[Any, tuple[float, float]] = {}  # device -> (seconds, measured_at)

# On the CPU platform host and mesh are the same silicon, so `auto` skips the
# probe entirely; tests flip this to exercise the placement switch with a
# monkeypatched probe.
_PROBE_CPU_MESH = False


def host_device() -> jax.Device:
    """The host CPU backend device (always present alongside TPU/GPU)."""
    return jax.devices("cpu")[0]


def dispatch_latency(device: jax.Device, *, samples: int = 5, max_age_s: Optional[float] = None) -> float:
    """Median round-trip seconds of a tiny jitted call on ``device``.

    Measures dispatch + completion + host fetch — the fixed cost every
    per-env-step player call pays regardless of model size. The measurement
    is cached; ``max_age_s`` bounds how stale a cached value may be
    (None = any age, the one-shot resolve path).
    """
    now = time.monotonic()
    hit = _latency_cache.get(device)
    if hit is not None and (max_age_s is None or now - hit[1] < max_age_s):
        return hit[0]
    f = jax.jit(lambda x: x + 1.0)
    x = jax.device_put(jnp.zeros((8,), jnp.float32), device)
    # Measuring device round-trip latency IS the point here; the sync is
    # the measurement, not an accident.
    jax.device_get(f(x))  # compile + warm path  # graftlint: disable=GL002
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        jax.device_get(f(x))  # graftlint: disable=GL002
        times.append(time.perf_counter() - t0)
    lat = sorted(times)[len(times) // 2]
    _latency_cache[device] = (lat, time.monotonic())
    return lat


def param_bytes(tree: Any) -> int:
    """Total bytes of all array leaves in a pytree."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype")
    )


def resolve_player_device(
    mode: str,
    mesh_device: jax.Device,
    *,
    params: Any = None,
    probe_max_age_s: Optional[float] = None,
) -> jax.Device:
    """Pick the device the player runs on. ``mode``: auto | host | mesh.

    ``probe_max_age_s`` bounds the latency-probe cache age (None = reuse any
    cached measurement; 0.0 = force a fresh probe — the TTL re-probe path).
    """
    mode = str(mode).lower()
    if mode not in ("auto", "host", "mesh"):
        raise ValueError(f"fabric.player_device must be one of auto|host|mesh, got {mode!r}")
    host = host_device()
    if mode == "host":
        return host
    if mode == "mesh" or (mesh_device.platform == "cpu" and not _PROBE_CPU_MESH):
        # On the CPU platform (tests, multichip dry runs) host and mesh are
        # the same silicon — nothing to win.
        return mesh_device
    if params is not None and param_bytes(params) > AUTO_MAX_PARAM_BYTES:
        return mesh_device
    # Probe a device THIS process can address: on a multi-host mesh the
    # global first device may belong to another process, and device_put onto
    # a non-addressable device raises.
    probe = next(
        (d for d in jax.local_devices() if d.platform == mesh_device.platform), None
    )
    if probe is None:
        return mesh_device
    lat = dispatch_latency(probe, max_age_s=probe_max_age_s)
    return host if lat > AUTO_LATENCY_THRESHOLD_S else mesh_device


def _all_ready(tree: Any) -> bool:
    for leaf in jax.tree_util.tree_leaves(tree):
        ready = getattr(leaf, "is_ready", None)
        if ready is not None and not ready():
            return False
    return True


class ParamMirror:
    """Keeps the player's copy of the training parameters on one device.

    ``push(params)`` is called after every optimizer step with the freshly
    updated (mesh-resident) parameters; ``get()`` is what the player reads.
    When the player device *is* the training device, both are pass-throughs.

    The copy travels PACKED: a jitted packer concatenates every leaf into one
    contiguous vector per dtype on the training device, so the device-to-host
    hop is one transfer instead of one per leaf — over a high-latency link a
    per-leaf ``device_put`` pays the full round trip ~#leaves times. (This is
    the role of the reference's ``parameters_to_vector`` broadcast,
    sac_decoupled.py:260-263.)

    The transfer leg runs on a worker thread: ``jax.device_put`` across
    devices blocks its calling thread for the whole copy (measured: the call
    itself takes the full transfer time over a remote link), so the main
    thread only packs (an async on-device dispatch) and hands the packed
    vectors over. In ``async`` mode at most one transfer is in flight with
    the NEWEST snapshot parked behind it (older waiting snapshots are the
    ones dropped); ``fresh`` mode submits every push and the next ``get()``
    waits for the last — tied-weights semantics, with the copy overlapping
    whatever the host does between update and next action.

    The pack runs immediately at push — never stashing the source arrays —
    because train steps donate their inputs: holding a reference for a
    deferred copy would read a deleted buffer. The worker only ever touches
    packed vectors, which nothing donates.
    """

    def __init__(self, device: Optional[jax.Device], *, sync: str = "fresh") -> None:
        sync = str(sync).lower()
        if sync not in ("fresh", "async"):
            raise ValueError(f"fabric.player_sync must be fresh|async, got {sync!r}")
        self.device = device
        self.sync = sync
        self._current: Any = None
        self._transfer = None  # Future of the in-flight D2H copy
        # Newest packed snapshot waiting behind an in-flight transfer
        # (async backpressure): at most one transfer in flight plus one
        # waiting snapshot, and the waiting slot always holds the NEWEST.
        self._next_packed: Any = None
        self._executor = None
        self._treedef = None
        self._shapes: Any = None
        self._dtypes: Any = None
        self._pack_fn = None
        self._unpack_fn = None
        self.pushes = 0
        self.skipped = 0

    # ------------------------------------------------------------- packing
    def _build_codec(self, params: Any) -> None:
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._shapes = [l.shape for l in leaves]
        self._dtypes = [jnp.dtype(l.dtype) for l in leaves]
        dtype_order = sorted({d.name for d in self._dtypes})

        def pack(tree):
            ls = jax.tree_util.tree_leaves(tree)
            out = {}
            for dname in dtype_order:
                out[dname] = jnp.concatenate(
                    [l.ravel() for l, d in zip(ls, self._dtypes) if d.name == dname]
                )
            return out

        def unpack(packed):
            offsets = {dname: 0 for dname in dtype_order}
            ls = []
            for shape, d in zip(self._shapes, self._dtypes):
                n = 1
                for dim in shape:
                    n *= int(dim)
                start = offsets[d.name]
                ls.append(packed[d.name][start : start + n].reshape(shape))
                offsets[d.name] = start + n
            return jax.tree_util.tree_unflatten(self._treedef, ls)

        self._pack_fn = jax.jit(pack)
        self._unpack_fn = jax.jit(unpack)

    def _unpack_on_device(self, packed: Any) -> Any:
        with jax.default_device(self.device):
            return self._unpack_fn(packed)

    # -------------------------------------------------------------- public
    def _submit(self, packed: Any):
        import concurrent.futures

        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sheeprl-mirror"
            )
        return self._executor.submit(jax.device_put, packed, self.device)

    def _promote(self, wait: bool = False) -> None:
        """Advance the pipeline: finished transfer -> current; waiting
        snapshot -> in-flight."""
        if self._transfer is not None and (
            wait or self._current is None or self._transfer.done()
        ):
            self._current = self._unpack_on_device(self._transfer.result())
            self._transfer = None
        if self._transfer is None and self._next_packed is not None:
            self._transfer = self._submit(self._next_packed)
            self._next_packed = None

    def push(self, params: Any) -> None:
        self.pushes += 1
        if self.device is None:  # player on the training device: share arrays
            self._current = params
            return
        # The trainer->player weight hop is the decoupled seam a distributed
        # trace needs visible: the span parents to the iteration that
        # produced these weights.
        with tracer_mod.current().span("player/mirror_push", "transfer", sync=self.sync):
            if self._pack_fn is None:
                self._build_codec(params)
            packed = self._pack_fn(params)
            if self.sync == "fresh" or self._transfer is None:
                # FIFO worker: in fresh mode every push transfers and get()
                # waits for the newest; replacing the Future reference keeps
                # exactly it.
                self._transfer = self._submit(packed)
                self._next_packed = None
                return
            if not self._transfer.done():
                # Backpressure: keep the in-flight transfer, park THIS
                # (newest) snapshot in the waiting slot — older waiting
                # snapshots are the ones dropped, so the newest always lands
                # eventually.
                if self._next_packed is not None:
                    self.skipped += 1
                self._next_packed = packed
                return
            self._promote()
            self._transfer = self._submit(packed)

    def get(self) -> Any:
        if self.device is not None:
            self._promote(wait=self.sync == "fresh")
        return self._current

    def flush(self) -> Any:
        """Block until the newest pushed snapshot is the served one.

        Call before final evaluation/checkpointing in async mode so results
        are reported for the trained weights, not a stale mirror.
        """
        if self.device is not None:
            with tracer_mod.current().span("player/mirror_flush", "transfer"):
                while self._transfer is not None or self._next_packed is not None:
                    self._promote(wait=True)
        return self._current

    def close(self) -> None:
        """Retire this mirror: drop any in-flight transfer and stop the
        worker thread. The served snapshot stays readable."""
        self._transfer = None
        self._next_packed = None
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None


class PlayerPlacement:
    """Bundle of (player device, parameter mirror, default-device context).

    Usage in an algorithm loop::

        placement = PlayerPlacement.resolve(cfg, mesh_device, params=actor_params)
        placement.push(actor_params)                  # initial mirror
        ...
        with placement.ctx():                         # per env step
            obs = prepare_obs(...)                    # arrays land player-side
            key, sub = jax.random.split(key)
            out = player_step_fn(placement.params(), obs, sub)
        ...
        placement.push(new_params)                    # after each train step
    """

    def __init__(self, device: jax.Device, mesh_device: jax.Device, sync: str, mode: str = "mesh") -> None:
        self.device = device
        self.on_mesh = device == mesh_device
        self.mirror = ParamMirror(None if self.on_mesh else device, sync=sync)
        self._mode = str(mode).lower()
        self._sync = sync
        self._mesh_device = mesh_device
        self._next_reprobe = time.monotonic() + AUTO_REPROBE_TTL_S
        self.placement_switches = 0

    @classmethod
    def resolve(
        cls,
        cfg: Any,
        mesh_device: jax.Device,
        *,
        params: Any = None,
        force_fresh: bool = False,
    ) -> "PlayerPlacement":
        fabric = cfg.get("fabric") if hasattr(cfg, "get") else getattr(cfg, "fabric", None)
        mode = (fabric.get("player_device") or "auto") if fabric is not None else "auto"
        sync = (fabric.get("player_sync") or "fresh") if fabric is not None else "fresh"
        if force_fresh:
            sync = "fresh"
        device = resolve_player_device(mode, mesh_device, params=params)
        return cls(device, mesh_device, sync, mode=mode)

    def _maybe_reprobe(self, params: Any = None) -> bool:
        """TTL'd re-evaluation of an `auto` placement: a link that degrades
        (or heals) mid-run flips the verdict at the next push past the TTL
        instead of persisting until restart. ``params`` (the tree about to
        be pushed) keeps the AUTO_MAX_PARAM_BYTES guard in force — an
        oversized player must stay on-mesh however slow the link gets.
        Returns True on a switch."""
        if self._mode != "auto" or (self._mesh_device.platform == "cpu" and not _PROBE_CPU_MESH):
            return False
        now = time.monotonic()
        if now < self._next_reprobe:
            return False
        self._next_reprobe = now + AUTO_REPROBE_TTL_S
        new_device = resolve_player_device(
            "auto", self._mesh_device, params=params, probe_max_age_s=0.0
        )
        if new_device == self.device:
            return False
        self.device = new_device
        self.on_mesh = new_device == self._mesh_device
        # A fresh mirror (old in-flight transfers target the old device); the
        # caller's push right after this lands the current weights on it.
        self.mirror.close()
        self.mirror = ParamMirror(None if self.on_mesh else new_device, sync=self._sync)
        self.placement_switches += 1
        return True

    def ctx(self):
        """Context manager placing new arrays (obs, PRNG keys) player-side.

        On-mesh this is a no-op: inputs stay uncommitted so jit resolves
        their placement from the (possibly multi-device) parameter sharding.
        """
        if self.on_mesh:
            return contextlib.nullcontext()
        return jax.default_device(self.device)

    def put(self, tree: Any) -> Any:
        """Commit a pytree (e.g. the rollout PRNG key) to the player device."""
        if self.on_mesh:
            return tree
        return jax.device_put(tree, self.device)

    def push(self, params: Any) -> None:
        # Re-probe BEFORE the push so a switch never strands these (newest)
        # weights in a mirror about to be replaced.
        self._maybe_reprobe(params)
        self.mirror.push(params)

    def params(self) -> Any:
        return self.mirror.get()
