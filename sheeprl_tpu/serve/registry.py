"""Registry mapping algorithm names to their serving policy adapters.

Mirrors the training-side pattern in :mod:`sheeprl_tpu.registry` (decorator
registration + a ``register_all`` that imports the per-algo modules), so a
checkpoint exported for any registered algorithm round-trips through
``serve`` without the serving core knowing algorithm internals. An adapter
class provides two halves of the contract:

- class method ``export(state, cfg) -> (params, config)`` — extract the
  inference-only params pytree from a training checkpoint ``state`` plus the
  (JSON-plain) config subtree the load side needs to rebuild the modules;
- constructor ``Adapter(spec, params)`` — rebuild the apply path from a
  loaded artifact, exposing ``pack_rows`` / ``make_apply`` /
  ``action_shape`` (and ``new_session`` when ``stateful``) to the engine.
"""

from __future__ import annotations

from typing import Dict, List, Type, Union

policy_registry: Dict[str, type] = {}
_REGISTERED = False


def register_policy(algorithms: Union[str, List[str]]):
    """Class decorator: register a policy adapter for one or more algorithm
    names (the ``cfg.algo.name`` recorded in the checkpoint's config)."""
    if isinstance(algorithms, str):
        algorithms = [algorithms]

    def decorator(cls: type) -> type:
        for name in algorithms:
            if name in policy_registry and policy_registry[name] is not cls:
                raise ValueError(
                    f"A policy adapter for algorithm {name!r} is already registered "
                    f"({policy_registry[name].__name__})"
                )
            policy_registry[name] = cls
        return cls

    return decorator


def register_all_policies() -> None:
    """Import every built-in serve adapter module (idempotent). Imports are
    guarded so one algo family's missing optional deps never takes down the
    others."""
    global _REGISTERED
    if _REGISTERED:
        return
    _REGISTERED = True
    import importlib

    for mod in (
        "sheeprl_tpu.algos.sac.serve",
        "sheeprl_tpu.algos.ppo.serve",
        "sheeprl_tpu.algos.dreamer_v3.serve",
    ):
        try:
            importlib.import_module(mod)
        except ImportError as err:  # pragma: no cover - optional-dep guard
            import warnings

            warnings.warn(f"Serve adapter module {mod} not importable: {err}")


def get_policy_cls(algo: str) -> Type:
    register_all_policies()
    try:
        return policy_registry[algo]
    except KeyError:
        raise KeyError(
            f"No serving adapter registered for algorithm {algo!r}. "
            f"Available: {sorted(policy_registry)}"
        ) from None
