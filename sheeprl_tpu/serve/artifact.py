"""Policy artifacts: self-contained, versioned inference snapshots.

A training checkpoint is the wrong unit to serve from: it drags optimizer
states and replay buffers, and rebuilding its apply path needs the full
training config plus an env to probe spaces from. ``export_artifact``
distills a checkpoint into a *policy artifact* — the inference params pytree,
the algorithm's apply-fn spec (the config subtree its modules rebuild from),
and the serialized obs/action spaces with preprocessing metadata — so the
serving host needs nothing but this directory and the ``sheeprl_tpu`` wheel.

Layout (committed with the same atomic staging discipline as checkpoints —
see :func:`sheeprl_tpu.utils.checkpoint.atomic_dir_writer`)::

    <name>.policy/
        arrays/         # Orbax tree: inference params only
        spec.json       # schema, algo, spaces, preprocessing, config subtree
        manifest.json   # digests over arrays + spec; written + fsynced last

``manifest.json`` carries content digests so a torn copy or bit rot is
detected at load; :func:`validate_artifact` is the serving analogue of
``validate_checkpoint``.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.utils.checkpoint import (
    _digest_arrays,
    atomic_dir_writer,
    parse_ckpt_name,
)

ARTIFACT_SUFFIX = ".policy"
SPEC_NAME = "spec.json"
MANIFEST_NAME = "manifest.json"
ARTIFACT_SCHEMA_VERSION = 1


# --------------------------------------------------------------- space specs
def space_to_spec(space) -> Dict[str, Any]:
    """Serialize a gymnasium space to a JSON-plain dict. Box bounds collapse
    to scalars when uniform (the common case — pixel 0..255, control ±1) so
    image specs stay small."""
    import gymnasium as gym

    if isinstance(space, gym.spaces.Dict):
        return {"type": "dict", "spaces": {k: space_to_spec(v) for k, v in space.spaces.items()}}
    if isinstance(space, gym.spaces.Box):
        low, high = np.asarray(space.low), np.asarray(space.high)
        return {
            "type": "box",
            "shape": list(space.shape),
            "dtype": np.dtype(space.dtype).name,
            "low": float(low.flat[0]) if np.all(low == low.flat[0]) else low.tolist(),
            "high": float(high.flat[0]) if np.all(high == high.flat[0]) else high.tolist(),
        }
    if isinstance(space, gym.spaces.Discrete):
        return {"type": "discrete", "n": int(space.n)}
    if isinstance(space, gym.spaces.MultiDiscrete):
        return {"type": "multi_discrete", "nvec": np.asarray(space.nvec).tolist()}
    raise TypeError(f"Cannot serialize space of type {type(space).__name__} into an artifact spec")


def spec_to_space(spec: Dict[str, Any]):
    import gymnasium as gym

    kind = spec["type"]
    if kind == "dict":
        return gym.spaces.Dict({k: spec_to_space(v) for k, v in spec["spaces"].items()})
    if kind == "box":
        shape = tuple(spec["shape"])
        dtype = np.dtype(spec["dtype"])
        low = np.broadcast_to(np.asarray(spec["low"], dtype), shape)
        high = np.broadcast_to(np.asarray(spec["high"], dtype), shape)
        return gym.spaces.Box(low=low, high=high, shape=shape, dtype=dtype)
    if kind == "discrete":
        return gym.spaces.Discrete(int(spec["n"]))
    if kind == "multi_discrete":
        return gym.spaces.MultiDiscrete(np.asarray(spec["nvec"], np.int64))
    raise TypeError(f"Unknown space spec type {kind!r}")


def _plain(obj: Any) -> Any:
    """Recursively convert dotdicts / numpy scalars into JSON-plain values."""
    if isinstance(obj, dict):
        return {str(k): _plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.tolist()  # numpy scalar -> python scalar, no .item() (GL002)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ----------------------------------------------------------------- artifacts
@dataclass(frozen=True)
class PolicyArtifact:
    """A loaded artifact: the parsed spec, the params pytree (numpy leaves),
    and where it came from."""

    path: str
    spec: Dict[str, Any]
    manifest: Dict[str, Any]
    params: Any

    @property
    def algo(self) -> str:
        return str(self.spec["algo"])

    @property
    def name(self) -> str:
        return str(self.spec.get("name", os.path.basename(self.path)))


def export_artifact(
    checkpoint_path: str,
    output_path: Optional[str] = None,
    *,
    name: Optional[str] = None,
    cfg: Optional[Any] = None,
) -> str:
    """Export ``checkpoint_path`` into a policy artifact directory.

    Runs on the training host: the run's ``config.yaml`` (next to the
    checkpoint dir) supplies the algorithm identity and the env factory the
    obs/action spaces are probed from — the produced artifact then needs
    neither. Returns the committed artifact path.
    """
    import yaml

    from sheeprl_tpu.serve.registry import get_policy_cls
    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    from sheeprl_tpu.utils.env import make_env
    from sheeprl_tpu.utils.utils import dotdict

    ckpt = pathlib.Path(checkpoint_path).absolute()
    if cfg is None:
        with open(ckpt.parent.parent / "config.yaml") as fp:
            cfg = dotdict(yaml.safe_load(fp))
    algo = str(cfg.algo.name)
    adapter_cls = get_policy_cls(algo)

    # Probe the spaces exactly as training saw them (wrappers applied), then
    # serialize them so serving never constructs an env.
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0)()
    try:
        obs_space, action_space = env.observation_space, env.action_space
        state = load_checkpoint(str(ckpt))
        params, policy_config = adapter_cls.export(state, cfg)
    finally:
        env.close()

    parsed = parse_ckpt_name(str(ckpt))
    step = parsed[0] if parsed else 0
    if name is None:
        name = f"{algo}_{cfg.env.id}_{step}"
    if output_path is None:
        output_path = str(ckpt.parent.parent / "artifacts" / f"{name}{ARTIFACT_SUFFIX}")

    spec = {
        "schema_version": ARTIFACT_SCHEMA_VERSION,
        "name": str(name),
        "algo": algo,
        "stateful": bool(getattr(adapter_cls, "stateful", False)),
        "policy_step": int(step),
        "source_checkpoint": str(ckpt),
        "env_id": str(cfg.env.id),
        "observation_space": space_to_spec(obs_space),
        "action_space": space_to_spec(action_space),
        "config": _plain(policy_config),
    }
    spec_bytes = json.dumps(spec, indent=2, sort_keys=True, default=str).encode()

    import jax
    import orbax.checkpoint as ocp

    np_params = jax.tree_util.tree_map(np.asarray, params)
    digest, leaf_count = _digest_arrays(np_params)
    manifest = {
        "schema_version": ARTIFACT_SCHEMA_VERSION,
        "kind": "policy_artifact",
        "algo": algo,
        "leaf_count": leaf_count,
        "digest": digest,
        "spec_sha256": _sha256_bytes(spec_bytes),
        "created_unix": time.time(),
    }

    with atomic_dir_writer(output_path, fail_point="artifact.before_commit") as staging:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.join(staging, "arrays"), np_params)
        with open(os.path.join(staging, SPEC_NAME), "wb") as fp:
            fp.write(spec_bytes)
            fp.flush()
            os.fsync(fp.fileno())
        with open(os.path.join(staging, MANIFEST_NAME), "w") as fp:
            json.dump(manifest, fp, indent=2)
            fp.flush()
            os.fsync(fp.fileno())
    return os.path.abspath(output_path)


def read_artifact_manifest(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, MANIFEST_NAME), "rb") as fp:
            manifest = json.load(fp)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def validate_artifact(path: str, verify_digest: bool = False) -> bool:
    """True iff ``path`` is a complete, committed policy artifact (structural
    check; ``verify_digest`` additionally rehashes spec + every array leaf)."""
    manifest = read_artifact_manifest(path)
    if manifest is None or manifest.get("kind") != "policy_artifact":
        return False
    try:
        if int(manifest["schema_version"]) > ARTIFACT_SCHEMA_VERSION:
            return False
        leaf_count = int(manifest["leaf_count"])
    except (KeyError, TypeError, ValueError):
        return False
    spec_file = os.path.join(path, SPEC_NAME)
    if not os.path.isdir(os.path.join(path, "arrays")) or not os.path.isfile(spec_file):
        return False
    if not verify_digest:
        return True
    try:
        with open(spec_file, "rb") as fp:
            if _sha256_bytes(fp.read()) != manifest.get("spec_sha256"):
                return False
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            arrays = ckptr.restore(os.path.abspath(os.path.join(path, "arrays")))
        digest, n = _digest_arrays(arrays)
        return n == leaf_count and digest == manifest.get("digest")
    except Exception:  # noqa: BLE001 - any unreadable payload means invalid
        return False


def load_artifact(path: str, *, verify_digest: bool = False) -> PolicyArtifact:
    """Load an artifact directory into spec + numpy params (no training
    config, no env, no Runtime needed)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not validate_artifact(path, verify_digest=verify_digest):
        raise ValueError(
            f"{path} is not a valid policy artifact (torn export, wrong schema, or failed "
            f"digest check) — re-run `python -m sheeprl_tpu.serve export checkpoint_path=...`"
        )
    with open(os.path.join(path, SPEC_NAME), "rb") as fp:
        spec = json.load(fp)
    manifest = read_artifact_manifest(path) or {}
    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(path, "arrays"))
    return PolicyArtifact(path=path, spec=spec, manifest=manifest, params=params)


def make_policy(artifact: PolicyArtifact):
    """Instantiate the registered adapter for a loaded artifact."""
    from sheeprl_tpu.serve.registry import get_policy_cls

    return get_policy_cls(artifact.algo)(artifact.spec, artifact.params)
