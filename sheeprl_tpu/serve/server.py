"""Stdlib-only HTTP front end for the inference engine.

One ``ThreadingHTTPServer`` (no third-party web stack — the serving path has
the same zero-new-dependencies rule as the rest of the repo) exposing:

- ``POST /v1/act`` — body ``{"model", "obs", "mode"?, "seed"?, "session"?,
  "deadline_s"?}``; responds ``{"action": [...], "session": ...}``;
- ``GET /v1/models`` — model cards for every hosted artifact plus engine
  stats (latency percentiles, occupancy, counters);
- ``GET /healthz`` — liveness + queue depth (load balancers poll this);
- ``GET /metrics`` — Prometheus text exposition (0.0.4) of the engine's
  :class:`~sheeprl_tpu.telemetry.MetricsRegistry` merged with the process
  default registry, so a scraper sees serving and training/telemetry
  metrics from one endpoint.

Engine exceptions map onto transport semantics: unknown model → 404, bad
request rows → 400, :class:`EngineOverloaded` → 429 with ``Retry-After``
(deadline-based shedding — the engine refuses work it cannot finish in
time rather than queueing it to die), :class:`RequestExpired` → 504, and a
draining engine → 503.

Causality: every request gets an ``X-Request-Id`` (the client's, or a
minted one) and a W3C ``traceparent`` context (a child of the client's, or
a fresh root). Both come back as response headers on EVERY reply —
including 4xx/5xx error paths — so a client can always correlate its call
with the server-side trace, and the engine's per-request spans join the
caller's trace across the wire. The access log is one structured
``key=value`` line per request (request_id, route, status, latency_ms,
batch bucket) on the ``sheeprl_tpu.serve.access`` logger; shed/drain
errors log at WARNING with the same ``Retry-After`` value the response
carries, which also lands them in the flight recorder's ring.

Shutdown reuses the resilience discipline: ``serve_forever`` installs a
:class:`~sheeprl_tpu.core.resilience.PreemptionGuard` (pointer writes off —
nothing to checkpoint) and on SIGTERM stops accepting connections, drains
the queue through ``engine.close(drain=True)``, then exits 0.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.serve.engine import (
    EngineClosed,
    EngineOverloaded,
    InferenceEngine,
    RequestExpired,
)
from sheeprl_tpu.telemetry import flight as flight_mod
from sheeprl_tpu.telemetry import trace_context
from sheeprl_tpu.telemetry.registry import (
    PROMETHEUS_CONTENT_TYPE,
    default_registry,
    merged_prometheus_text,
)

_ACCESS_LOG = logging.getLogger("sheeprl_tpu.serve.access")


def _json_bytes(payload: Dict[str, Any]) -> bytes:
    return json.dumps(payload).encode("utf-8")


class _Handler(BaseHTTPRequestHandler):
    # Set by PolicyServer before the server starts.
    engine: InferenceEngine

    server_version = "sheeprl-tpu-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, format: str, *args: Any) -> None:  # noqa: A002
        pass  # the structured access log below replaces the stdlib line

    # ------------------------------------------------------------- plumbing
    def _begin_request(self) -> None:
        """Accept-or-mint the request id and trace context; one call at the
        top of every route handler."""
        self._t_start = time.perf_counter()
        self._status: Optional[int] = None
        self._retry_after: Optional[str] = None
        self._bucket: Optional[int] = None
        rid = (self.headers.get("X-Request-Id") or "").strip()
        self._request_id = rid or uuid.uuid4().hex
        parent = trace_context.TraceContext.from_traceparent(
            self.headers.get("traceparent") or ""
        )
        self._ctx = trace_context.mint(parent)

    def _log_access(self, route: str) -> None:
        latency_ms = (time.perf_counter() - self._t_start) * 1e3
        status = self._status if self._status is not None else 0
        line = (
            f"request_id={self._request_id} route={route} status={status} "
            f"latency_ms={latency_ms:.2f} bucket={self._bucket if self._bucket is not None else '-'}"
        )
        if self._retry_after is not None:
            # Retry-After in the log matches the header byte-for-byte, so an
            # operator grepping the access log sees the same backoff a client
            # was told. WARNING level also lands it in the flight ring.
            _ACCESS_LOG.warning("%s retry_after_s=%s", line, self._retry_after)
        elif status >= 500:
            _ACCESS_LOG.warning(line)
        else:
            _ACCESS_LOG.info(line)

    def _reply_raw(
        self,
        status: int,
        body: bytes,
        content_type: str,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        # Correlation headers on EVERY reply, error paths included: the
        # client can always tie its call to the server-side trace.
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
        ctx = getattr(self, "_ctx", None)
        if ctx is not None:
            self.send_header("traceparent", ctx.to_traceparent())
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)
        self._status = status
        self._retry_after = (headers or {}).get("Retry-After")

    def _reply(self, status: int, payload: Dict[str, Any], headers: Optional[Dict[str, str]] = None) -> None:
        self._reply_raw(status, _json_bytes(payload), "application/json", headers)

    def _error(self, status: int, message: str, headers: Optional[Dict[str, str]] = None) -> None:
        self._reply(status, {"error": message, "request_id": getattr(self, "_request_id", None)}, headers)

    # --------------------------------------------------------------- routes
    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        self._begin_request()
        if self.path == "/healthz":
            stats = self.engine.stats()
            self._reply(200, {"status": "ok", "queue_depth": stats["queue_depth"], "models": stats["models"]})
        elif self.path == "/v1/models":
            self._reply(200, {"models": self.engine.models(), "stats": self.engine.stats()})
        elif self.path.split("?")[0] == "/metrics":
            body = merged_prometheus_text([self.engine.registry, default_registry()])
            self._reply_raw(200, body.encode("utf-8"), PROMETHEUS_CONTENT_TYPE)
        else:
            self._error(404, f"no route for GET {self.path}")
        self._log_access(f"GET {self.path.split('?')[0]}")

    def do_POST(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        self._begin_request()
        try:
            self._do_post_inner()
        finally:
            self._log_access(f"POST {self.path.split('?')[0]}")

    def _do_post_inner(self) -> None:
        if self.path != "/v1/act":
            self._error(404, f"no route for POST {self.path}")
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            request = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(request, dict):
                raise ValueError("request body must be a JSON object")
            model = request["model"]
            obs = request["obs"]
        except (KeyError, ValueError, json.JSONDecodeError) as err:
            self._error(400, f"malformed request: {err}")
            return
        deadline_s = request.get("deadline_s")
        try:
            # The request's context is current for the duration of the engine
            # call: the submit path captures it onto the queued request, so
            # the dispatcher's per-request span joins this client's trace.
            with trace_context.use(self._ctx):
                action, info = self.engine.act_with_info(
                    str(model),
                    obs,
                    mode=str(request.get("mode", "greedy")),
                    seed=int(request.get("seed", 0)),
                    session=request.get("session"),
                    deadline_s=float(deadline_s) if deadline_s is not None else None,
                    request_id=self._request_id,
                )
        except KeyError as err:
            self._error(404, str(err))
        except ValueError as err:
            self._error(400, str(err))
        except EngineOverloaded as err:
            self._error(429, str(err), {"Retry-After": f"{err.retry_after_s:.3f}"})
        except RequestExpired as err:
            self._error(504, str(err))
        except EngineClosed as err:
            self._error(503, str(err))
        else:
            self._bucket = info.get("bucket")
            self._reply(
                200,
                {
                    "model": str(model),
                    "action": np.asarray(action).tolist(),
                    "session": request.get("session"),
                    "request_id": self._request_id,
                },
            )


class PolicyServer:
    """Own an engine + HTTP listener pair.

    ``start()`` binds and serves on a daemon thread (tests, in-process use);
    ``serve_forever()`` is the CLI path — foreground with SIGTERM drain.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        host: str = "127.0.0.1",
        port: int = 8080,
        trace_dir: Optional[str] = None,
    ) -> None:
        self.engine = engine
        # Serve processes run without the training Telemetry facade, so the
        # always-on flight recorder is installed here: overload sheds and
        # crashes produce a dump like any training trip. ``trace_dir`` gives
        # the dumps a home; without one the ring still records (and a later
        # installer can supply a directory).
        if flight_mod.current() is None:
            flight_mod.install(
                flight_mod.FlightRecorder(trace_dir=trace_dir, run_info={"role": "serve"})
            )
        flight_mod.ensure_live_tracer()
        handler = type("BoundHandler", (_Handler,), {"engine": engine})
        self._http = ThreadingHTTPServer((host, port), handler)
        self._http.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._http.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "PolicyServer":
        self._thread = threading.Thread(target=self._http.serve_forever, name="serve-http", daemon=True)
        self._thread.start()
        return self

    def close(self, drain: bool = True) -> None:
        self._http.shutdown()
        self._http.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.engine.close(drain=drain)

    def serve_forever(self, poll_s: float = 0.25) -> None:
        """Foreground serve with graceful preemption: SIGTERM (or Ctrl-C)
        flips the guard, we stop accepting connections, drain the queue,
        and return — the k8s-friendly exit the training loops already use."""
        from sheeprl_tpu.core.resilience import PreemptionGuard

        guard = PreemptionGuard(enabled=True, write_pointer=False).install()
        self.start()
        try:
            while not guard.preempted:
                time.sleep(poll_s)
        finally:
            self.close(drain=True)
            guard.close()


class ServeClient:
    """In-process client mirroring the HTTP surface (bench legs and tests
    exercise the exact engine semantics without a socket in the loop)."""

    def __init__(self, engine: InferenceEngine) -> None:
        self.engine = engine

    def act(
        self,
        model: str,
        obs: Dict[str, Any],
        *,
        mode: str = "greedy",
        seed: int = 0,
        session: Optional[str] = None,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = 30.0,
    ) -> np.ndarray:
        return self.engine.act(
            model, obs, mode=mode, seed=seed, session=session, deadline_s=deadline_s, timeout=timeout
        )

    def models(self) -> Dict[str, Any]:
        return self.engine.models()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()
