"""Dynamic micro-batching inference engine.

EnvPool's lesson (arXiv:2206.10558) applies on the serving side too: the
wins come from batching the request-facing half, not faster kernels. The
engine turns a stream of single-observation requests into batched, compiled
policy applies:

- requests land in a bounded FIFO; a dispatcher thread drains the head run
  of same-(model, mode) requests into one batch (at most one request per
  recurrent session), optionally lingering ``batch_window_s`` to fill it;
- batches are padded to power-of-two buckets, exactly the trick
  ``algo.fused_train_steps`` uses — the compiled-graph population is bounded
  at log2(max_batch)+1 variants per (model, mode), all warmed up at load so
  no request ever pays a compile;
- each batch is ONE jitted apply (session state donated for recurrent
  policies) followed by ONE coalesced ``device_get`` for the actions — the
  dispatcher body holds no other host syncs;
- actions are stochastic-by-seed (``jax.random.PRNGKey(seed)`` per row, the
  same derivation the evaluate paths use) or greedy; both are deterministic
  functions of (artifact, obs, seed) so responses are replayable;
- multiple artifacts are hosted concurrently with LRU eviction past
  ``max_models``.

Telemetry: every engine metric lives in a
:class:`~sheeprl_tpu.telemetry.MetricsRegistry` (one per engine, or an
injected shared one): request latency is a registry histogram (p50/p95/p99
via ``stats()``), queue depth and batch occupancy are registry gauges, and
sheds/timeouts/errors/evictions are registry counters. ``stats()``, the
server's ``GET /metrics`` Prometheus rendering, and the tracer mirrors in
``telemetry.jsonl`` all read the same objects, so the three surfaces can
never disagree.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.serve.artifact import PolicyArtifact, load_artifact, make_policy
from sheeprl_tpu.telemetry import flight as flight_mod
from sheeprl_tpu.telemetry import trace_context
from sheeprl_tpu.telemetry import tracer as tracer_mod
from sheeprl_tpu.telemetry.registry import MetricsRegistry

MODES = ("greedy", "sample")

#: Engine counter short names; registered as ``serve/<name>`` in the registry.
COUNTER_KEYS = ("requests", "batches", "sheds", "timeouts", "errors", "evictions")


class EngineClosed(RuntimeError):
    """The engine is shut down (requests are not accepted)."""


class EngineOverloaded(RuntimeError):
    """Backpressure signal: queue full, or the estimated wait exceeds the
    request deadline. Carries ``retry_after_s`` for the server's 429."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class RequestExpired(TimeoutError):
    """The request's deadline passed while it waited in the queue."""


def next_pow2(n: int) -> int:
    return 1 << (max(int(n), 1) - 1).bit_length()


@dataclass
class _Request:
    model: str
    mode: str
    obs: Any
    seed: int
    session: Optional[str]
    deadline_t: Optional[float]  # absolute monotonic deadline, None = no deadline
    future: Future
    t_submit: float
    # Causality: the trace context active on the SUBMITTING thread (contextvars
    # do not cross into the dispatcher thread, so it rides on the request) plus
    # the caller-facing request id for the access log.
    ctx: Optional[trace_context.TraceContext] = None
    request_id: Optional[str] = None


@dataclass
class _HostedModel:
    name: str
    artifact: Optional[PolicyArtifact]
    adapter: Any
    applies: Dict[str, Any] = field(default_factory=dict)
    sessions: "OrderedDict[str, Any]" = field(default_factory=OrderedDict)
    dummy_session: Any = None


class InferenceEngine:
    def __init__(
        self,
        *,
        max_batch: int = 8,
        queue_capacity: int = 64,
        batch_window_s: float = 0.002,
        max_models: int = 4,
        max_sessions: int = 256,
        autostart: bool = True,
        registry: Optional[MetricsRegistry] = None,
        goodput: bool = True,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = next_pow2(max_batch)
        self.buckets = [1 << i for i in range((self.max_batch).bit_length())]
        self.buckets = [b for b in self.buckets if b <= self.max_batch]
        self.queue_capacity = int(queue_capacity)
        self.batch_window_s = float(batch_window_s)
        self.max_models = int(max_models)
        self.max_sessions = int(max_sessions)

        self._models: "OrderedDict[str, _HostedModel]" = OrderedDict()  # graftlint: guarded-by(self._cv)
        self._queue: deque = deque()  # graftlint: guarded-by(self._cv)
        self._cv = threading.Condition()
        self._stop = False  # graftlint: guarded-by(self._cv)
        self._drain_on_close = True  # graftlint: guarded-by(self._cv)
        self._thread: Optional[threading.Thread] = None

        # Registry-backed metrics: ``stats()`` and the server's ``/metrics``
        # rendering read these same objects. A private registry per engine by
        # default so concurrent engines (tests, multi-tenant) don't mix.
        self.registry = registry if registry is not None else MetricsRegistry()
        self.latency = self.registry.histogram("serve/latency_s")
        self._counters = {key: self.registry.counter(f"serve/{key}") for key in COUNTER_KEYS}
        self._queue_depth_gauge = self.registry.gauge("serve/queue_depth")
        self._occupancy_gauge = self.registry.gauge("serve/batch_occupancy")
        # Roofline goodput accounting over the serve jits: cost specs noted at
        # warm-up/dispatch, published into this engine's registry by stats().
        from sheeprl_tpu.telemetry.perf import PerfAccountant

        self.perf = PerfAccountant(enabled=bool(goodput), registry=self.registry)
        # Device provenance gauges: which hardware this engine serves on,
        # scrape-visible so a fleet dashboard can group replicas by backend
        # (the serve-side mirror of the trainer's telemetry meta stamps).
        try:
            from sheeprl_tpu.telemetry.mesh_obs import device_provenance

            provenance = device_provenance()
            if provenance.get("device_count"):
                self.registry.gauge("serve/device_count").set(float(provenance["device_count"]))
                self.registry.gauge("serve/process_index").set(float(provenance.get("process_index", 0)))
        except Exception:  # noqa: BLE001 - metrics bridge must not block serving
            pass
        # bucket -> [requests_served, batches] for mean-occupancy reporting.
        # Written by the dispatcher thread, cleared by reset_stats() from
        # HTTP/bench threads — both sides must hold the condition's lock.
        self._occupancy: Dict[int, List[int]] = {}  # graftlint: guarded-by(self._cv)
        self._ewma_service_s: Optional[float] = None  # graftlint: guarded-by(self._cv)
        # Serve processes have no JaxEventMonitor; the module listeners still
        # mirror compile/retrace/cache traffic into the default registry so
        # ``/metrics`` shows the jax/* counters (warm-up compiles included).
        try:
            from sheeprl_tpu.telemetry import jax_events

            jax_events.install_listeners()
        except Exception:  # noqa: BLE001 - metrics bridge must not block serving
            pass
        if autostart:
            self.start()

    @property
    def counters(self) -> Dict[str, int]:
        """Point-in-time integer view of the registry-backed engine counters."""
        return {key: int(counter.value) for key, counter in self._counters.items()}

    def _count(self, key: str, amount: int = 1) -> None:
        self._counters[key].inc(amount)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, name="serve-dispatcher", daemon=True)
        self._thread.start()

    def close(self, drain: bool = True) -> None:
        """Stop the dispatcher. ``drain=True`` (the SIGTERM path) serves every
        queued request first; ``drain=False`` fails them with EngineClosed."""
        with self._cv:
            self._stop = True
            self._drain_on_close = bool(drain)
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        leftovers: List[_Request] = []
        with self._cv:
            while self._queue:
                leftovers.append(self._queue.popleft())
        for req in leftovers:
            req.future.set_exception(EngineClosed("engine closed before the request was served"))

    # --------------------------------------------------------- model hosting
    def load(self, name: str, path: str, *, warmup: bool = True) -> Dict[str, Any]:
        """Load an artifact under ``name``, compile every (mode, bucket)
        variant, and evict the least-recently-used model past ``max_models``."""
        artifact = load_artifact(path)
        return self.host(name, make_policy(artifact), artifact=artifact, warmup=warmup)

    def host(
        self,
        name: str,
        adapter: Any,
        *,
        artifact: Optional[PolicyArtifact] = None,
        warmup: bool = True,
    ) -> Dict[str, Any]:
        """Mount an already-constructed adapter (the in-process path ``load``
        goes through after reading an artifact from disk)."""
        import jax

        model = _HostedModel(name=name, artifact=artifact, adapter=adapter)
        for mode in MODES:
            donate = (3,) if adapter.stateful else ()
            model.applies[mode] = jax.jit(
                adapter.make_apply(greedy=(mode == "greedy")), donate_argnums=donate
            )
        if adapter.stateful:
            model.dummy_session = adapter.new_session(0)
        if warmup:
            self._warmup(model)
        evicted: List[str] = []
        with self._cv:
            self._models[name] = model
            self._models.move_to_end(name)
            while len(self._models) > self.max_models:
                victim, _ = self._models.popitem(last=False)
                evicted.append(victim)
                self._count("evictions")
        trc = tracer_mod.current()
        trc.count("serve_models_loaded", 1)
        for victim in evicted:
            trc.count("serve_models_evicted", 1)
        return adapter.describe()

    def _warmup(self, model: _HostedModel) -> None:
        """Populate the jit cache for every (mode, bucket) so no live request
        pays a compile. Dispatch-only (no block): compilation happens at
        trace time; execution of the zero batches can overlap freely."""
        start = time.perf_counter()
        for mode in MODES:
            for bucket in self.buckets:
                obs = model.adapter.pack_rows([], bucket)
                seeds = np.zeros((bucket,), np.uint32)
                state = self._stack_sessions(model, [model.dummy_session] * bucket) if model.adapter.stateful else None
                # steps=0: warm-up captures the cost specs without crediting
                # served work; live dispatches count via _dispatch_batch.
                self.perf.note(
                    f"serve/{mode}_b{bucket}", model.applies[mode],
                    (model.adapter.params, obs, seeds, state), steps=0,
                )
                model.applies[mode](model.adapter.params, obs, seeds, state)
        tracer_mod.current().add_span(
            "serve/warmup",
            "serve",
            start,
            time.perf_counter() - start,
            {"model": model.name, "buckets": list(self.buckets)},
        )

    def unload(self, name: str) -> None:
        with self._cv:
            self._models.pop(name, None)

    def models(self) -> Dict[str, Dict[str, Any]]:
        with self._cv:
            hosted = list(self._models.items())
        return {name: model.adapter.describe() for name, model in hosted}

    # --------------------------------------------------------------- ingress
    def estimated_wait_s(self) -> float:
        """Queue depth x EWMA per-request service time: the admission
        estimate the deadline shed compares against."""
        ewma = self._ewma_service_s or 0.0
        return (len(self._queue) + 1) * ewma

    def submit(
        self,
        model: str,
        obs: Any,
        *,
        mode: str = "greedy",
        seed: int = 0,
        session: Optional[str] = None,
        deadline_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> Future:
        """Enqueue one observation; returns a Future resolving to the action
        row (numpy). Raises KeyError (unknown model), ValueError (bad mode /
        malformed obs / missing session), EngineOverloaded (shed), or
        EngineClosed."""
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        with self._cv:
            if self._stop:
                raise EngineClosed("engine is shutting down")
            hosted = self._models.get(model)
        if hosted is None:
            raise KeyError(f"No model named {model!r} is loaded. Loaded: {sorted(self.models())}")
        if hosted.adapter.stateful and session is None:
            raise ValueError(
                f"model {model!r} is recurrent: requests must carry a session id "
                "(any stable string; state is kept per session)"
            )
        row = hosted.adapter.normalize_row(obs)

        if deadline_s is not None and self.estimated_wait_s() > float(deadline_s):
            self._count("sheds")
            tracer_mod.current().count("serve_sheds", 1)
            flight_mod.dump_on_trip(
                "engine_overload",
                message=f"deadline shed: estimated wait {self.estimated_wait_s():.3f}s",
                args={
                    "queue_depth": len(self._queue),
                    "capacity": self.queue_capacity,
                    "request_id": request_id,
                },
            )
            raise EngineOverloaded(
                f"estimated wait {self.estimated_wait_s():.3f}s exceeds the request "
                f"deadline {float(deadline_s):.3f}s",
                retry_after_s=max(self.estimated_wait_s(), 0.05),
            )
        fut: Future = Future()
        req = _Request(
            model=model,
            mode=mode,
            obs=row,
            seed=int(seed),
            session=session,
            deadline_t=(time.monotonic() + float(deadline_s)) if deadline_s is not None else None,
            future=fut,
            t_submit=time.perf_counter(),
            ctx=trace_context.current(),
            request_id=request_id,
        )
        overloaded: Optional[EngineOverloaded] = None
        with self._cv:
            if self._stop:
                raise EngineClosed("engine is shutting down")
            if len(self._queue) >= self.queue_capacity:
                self._count("sheds")
                tracer_mod.current().count("serve_sheds", 1)
                overloaded = EngineOverloaded(
                    f"request queue is full ({self.queue_capacity})",
                    retry_after_s=max(self.estimated_wait_s(), 0.05),
                )
            else:
                self._queue.append(req)
                self._count("requests")
                self._queue_depth_gauge.set(float(len(self._queue)))
                self._cv.notify_all()
        if overloaded is not None:
            # Flight dump OUTSIDE the lock: the recorder merges spill files on
            # a trip, which must not stall the dispatcher or other submitters.
            flight_mod.dump_on_trip(
                "engine_overload",
                message=f"queue-full shed ({self.queue_capacity} queued)",
                args={
                    "queue_depth": self.queue_capacity,
                    "capacity": self.queue_capacity,
                    "request_id": request_id,
                },
            )
            raise overloaded
        return fut

    def act(
        self,
        model: str,
        obs: Any,
        *,
        mode: str = "greedy",
        seed: int = 0,
        session: Optional[str] = None,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = 30.0,
    ) -> np.ndarray:
        """Synchronous submit + wait (the in-process client path)."""
        return self.submit(
            model, obs, mode=mode, seed=seed, session=session, deadline_s=deadline_s
        ).result(timeout=timeout)

    def act_with_info(
        self,
        model: str,
        obs: Any,
        *,
        mode: str = "greedy",
        seed: int = 0,
        session: Optional[str] = None,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = 30.0,
        request_id: Optional[str] = None,
    ) -> "tuple[np.ndarray, Dict[str, Any]]":
        """``act`` plus the per-request dispatch info (bucket, queue-wait,
        trace ids) the server's access log wants. The info dict is stamped on
        the future by the dispatcher before the result is set."""
        fut = self.submit(
            model,
            obs,
            mode=mode,
            seed=seed,
            session=session,
            deadline_s=deadline_s,
            request_id=request_id,
        )
        action = fut.result(timeout=timeout)
        info = dict(getattr(fut, "request_info", None) or {})
        return action, info

    def new_session_id(self) -> str:
        return uuid.uuid4().hex

    def end_session(self, model: str, session: str) -> None:
        with self._cv:
            hosted = self._models.get(model)
        if hosted is not None:
            hosted.sessions.pop(session, None)

    # ------------------------------------------------------------ dispatcher
    def _run(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            self._dispatch_batch(batch)

    def _next_batch(self) -> Optional[List[_Request]]:
        """Block for the next head-of-line run of batchable requests; None
        means the dispatcher should exit (stopped and nothing left to drain)."""
        with self._cv:
            while True:
                if self._queue:
                    break
                if self._stop:
                    return None
                self._cv.wait(timeout=0.1)
            if not self._stop and self.batch_window_s > 0 and len(self._queue) < self.max_batch:
                # Linger briefly to let the batch fill — bounded, and skipped
                # entirely during drain.
                deadline = time.monotonic() + self.batch_window_s
                while len(self._queue) < self.max_batch and not self._stop:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
            batch = [self._queue.popleft()]
            sessions = {batch[0].session}
            while self._queue and len(batch) < self.max_batch:
                head: _Request = self._queue[0]
                same_group = head.model == batch[0].model and head.mode == batch[0].mode
                # One request per recurrent session per batch: a session's
                # state advances once per apply.
                session_free = head.session is None or head.session not in sessions
                if not (same_group and session_free):
                    break
                batch.append(self._queue.popleft())
                sessions.add(head.session)
            return batch

    def _get_session(self, model: _HostedModel, req: _Request) -> Any:
        state = model.sessions.get(req.session)
        if state is None:
            state = model.adapter.new_session(req.seed)
            model.sessions[req.session] = state
            while len(model.sessions) > self.max_sessions:
                model.sessions.popitem(last=False)
        model.sessions.move_to_end(req.session)
        return state

    @staticmethod
    def _stack_sessions(model: _HostedModel, rows: List[Any]) -> Any:
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rows)

    def _dispatch_batch(self, batch: List[_Request]) -> None:
        import jax

        t_dispatch = time.perf_counter()  # queue-wait ends here for every row
        now = time.monotonic()
        live: List[_Request] = []
        for req in batch:
            if req.deadline_t is not None and now > req.deadline_t:
                self._count("timeouts")
                tracer_mod.current().count("serve_timeouts", 1)
                req.future.set_exception(
                    RequestExpired("deadline passed while the request waited in the queue")
                )
            else:
                live.append(req)
        if not live:
            return
        with self._cv:
            model = self._models.get(live[0].model)
            if model is not None:
                self._models.move_to_end(live[0].model)
        if model is None:
            for req in live:
                req.future.set_exception(KeyError(f"model {live[0].model!r} was evicted"))
            return

        mode = live[0].mode
        bucket = min(next_pow2(len(live)), self.max_batch)
        obs = model.adapter.pack_rows([r.obs for r in live], bucket)
        seeds = np.zeros((bucket,), np.uint32)
        for i, req in enumerate(live):
            seeds[i] = np.uint32(req.seed)
        state = None
        if model.adapter.stateful:
            rows = [self._get_session(model, req) for req in live]
            rows.extend([model.dummy_session] * (bucket - len(live)))
            state = self._stack_sessions(model, rows)

        # Goodput accounting BEFORE the apply (stateful adapters donate the
        # session state): one key per (mode, bucket) program variant.
        self.perf.note(
            f"serve/{mode}_b{bucket}", model.applies[mode],
            (model.adapter.params, obs, seeds, state), steps=len(live),
        )
        start = time.perf_counter()
        try:
            actions, new_state = model.applies[mode](model.adapter.params, obs, seeds, state)
            t_apply = time.perf_counter()
            # ONE coalesced host transfer per batch: the action rows. Session
            # states stay on device (sliced lazily below).
            host_actions = np.asarray(jax.device_get(actions))
        except Exception as err:  # noqa: BLE001 - any apply failure fails the batch
            self._count("errors")
            tracer_mod.current().count("serve_errors", 1)
            for req in live:
                req.future.set_exception(err)
            return
        elapsed = time.perf_counter() - start
        device_s = t_apply - start  # dispatch + (sync backends) execute
        harvest_s = elapsed - device_s  # device_get: where async backends block
        # Apply + harvest is the batch's device-bound share for the goodput
        # breakdown (the engine carries no StepTimer).
        self.perf.add_compute(elapsed)
        if model.adapter.stateful:
            for i, req in enumerate(live):
                model.sessions[req.session] = jax.tree_util.tree_map(lambda x: x[i], new_state)

        per_request = elapsed / len(live)
        with self._cv:
            # reset_stats() clears the occupancy table from bench/HTTP threads
            # mid-run; unlocked setdefault here would resurrect a dead bucket
            # row and double-count against the post-reset window.
            prev = self._ewma_service_s
            self._ewma_service_s = per_request if prev is None else 0.2 * per_request + 0.8 * prev
            occ = self._occupancy.setdefault(bucket, [0, 0])
            occ[0] += len(live)
            occ[1] += 1
        self._count("batches")

        # Causality: every request span is a child of ITS caller's trace (the
        # context captured at submit — contextvars don't reach this thread),
        # and the batch span carries ``links`` naming each request it padded
        # in, so a request id resolves to the exact batch that served it.
        req_ctxs: List[Optional[trace_context.TraceContext]] = [
            req.ctx.child() if req.ctx is not None else None for req in live
        ]
        batch_parent = next((c for c in req_ctxs if c is not None), None)
        batch_ctx = trace_context.mint(batch_parent)
        links = [
            {
                "request_id": req.request_id,
                "trace_id": rctx.trace_id if rctx is not None else None,
                "span_id": rctx.span_id if rctx is not None else None,
            }
            for req, rctx in zip(live, req_ctxs)
        ]

        trc = tracer_mod.current()
        trc.add_span(
            "serve/batch",
            "serve",
            start,
            elapsed,
            {
                "model": model.name,
                "mode": mode,
                "bucket": bucket,
                "occupancy": len(live),
                "links": links,
            },
            ctx=batch_ctx,
        )
        trc.count("serve_batches", 1)
        trc.count("serve_requests_served", len(live))
        queue_depth = float(len(self._queue))
        occupancy_frac = float(len(live)) / float(bucket)
        self._queue_depth_gauge.set(queue_depth)
        self._occupancy_gauge.set(occupancy_frac)
        trc.set_gauge("serve/queue_depth", queue_depth)
        trc.set_gauge("serve/batch_occupancy", occupancy_frac)

        done = time.perf_counter()
        for i, req in enumerate(live):
            self.latency.record(done - req.t_submit)
            queue_wait_s = max(t_dispatch - req.t_submit, 0.0)
            info = {
                "request_id": req.request_id,
                "bucket": bucket,
                "queue_wait_s": queue_wait_s,
                "device_s": device_s,
                "harvest_s": harvest_s,
                "batch_span": batch_ctx.span_id,
                "batch_trace": batch_ctx.trace_id,
            }
            trc.add_span(
                "serve/request",
                "serve",
                req.t_submit,
                done - req.t_submit,
                dict(info),
                ctx=req_ctxs[i],
            )
            # Stamped BEFORE set_result so act_with_info sees it on wake.
            req.future.request_info = info  # type: ignore[attr-defined]
            req.future.set_result(host_actions[i])

    # ----------------------------------------------------------------- stats
    def reset_stats(self) -> None:
        """Zero the latency histogram, occupancy table, and counters (bench
        sweeps measure per-leg windows); the service-time EWMA is kept."""
        with self._cv:
            self.latency.reset()
            self._occupancy.clear()
            for counter in self._counters.values():
                counter.reset()

    def stats(self) -> Dict[str, Any]:
        # Publish the goodput interval into the engine registry so a stats
        # poll and a /metrics scrape report the same perf/* gauges.
        goodput = self.perf.publish()
        occupancy = {
            str(bucket): {
                "batches": int(batches),
                "mean_occupancy": (served / batches) if batches else 0.0,
            }
            for bucket, (served, batches) in sorted(self._occupancy.items())
        }
        return {
            "queue_depth": len(self._queue),
            "counters": dict(self.counters),
            "latency": self.latency.summary(),
            "ewma_service_s": self._ewma_service_s,
            "occupancy": occupancy,
            "models": sorted(self._models),
            "buckets": list(self.buckets),
            "goodput": goodput,
        }
