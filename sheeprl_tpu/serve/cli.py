"""Command-line entry points: ``python -m sheeprl_tpu.serve <export|serve>``.

``export`` distills a training checkpoint into a self-contained policy
artifact (no training config or replay state needed to load it later)::

    python -m sheeprl_tpu.serve export checkpoint_path=logs/.../ckpt_1024 \
        [output_path=my_policy.policy] [name=my_policy]

``serve`` composes the ``serve_config`` root (the same Hydra-lite machinery
every other entry point uses), loads the listed artifacts into an engine,
and runs the HTTP server in the foreground until SIGTERM::

    python -m sheeprl_tpu.serve serve 'artifacts=["my_policy.policy"]' \
        serve.port=8080 serve.max_batch=8
"""

from __future__ import annotations

import pathlib
import sys
from typing import List, Optional, Sequence


def _export(overrides: List[str]) -> None:
    from sheeprl_tpu.serve.artifact import export_artifact

    kwargs = {}
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"export arguments are key=value pairs, got {ov!r}")
        k, v = ov.split("=", 1)
        kwargs[k.lstrip("+")] = v
    checkpoint_path = kwargs.pop("checkpoint_path", None)
    if checkpoint_path is None:
        raise ValueError("You must specify checkpoint_path=<path-to-checkpoint>")
    output_path = kwargs.pop("output_path", None)
    name = kwargs.pop("name", None)
    if kwargs:
        raise ValueError(f"Unknown export arguments: {sorted(kwargs)}")
    path = export_artifact(checkpoint_path, output_path, name=name)
    print(f"Exported policy artifact: {path}")


def _serve(overrides: List[str]) -> None:
    import sheeprl_tpu
    from sheeprl_tpu.config.loader import compose
    from sheeprl_tpu.serve.engine import InferenceEngine
    from sheeprl_tpu.serve.server import PolicyServer

    sheeprl_tpu.register_all()
    cfg = compose("serve_config", overrides)
    artifacts = cfg.get("artifacts") or []
    if artifacts == "???" or not isinstance(artifacts, (list, tuple)) or not artifacts:
        raise ValueError(
            "You must specify at least one artifact: 'artifacts=[\"path/to/policy.policy\"]'"
        )
    serve_cfg = cfg.serve
    engine = InferenceEngine(
        max_batch=int(serve_cfg.max_batch),
        queue_capacity=int(serve_cfg.queue_capacity),
        batch_window_s=float(serve_cfg.batch_window_ms) / 1000.0,
        max_models=int(serve_cfg.max_models),
        max_sessions=int(serve_cfg.max_sessions),
    )
    for entry in artifacts:
        path = pathlib.Path(str(entry))
        name = path.name[: -len(".policy")] if path.name.endswith(".policy") else path.name
        card = engine.load(name, str(path))
        print(f"Loaded model {name!r} ({card['algo']}) from {path}")
    server = PolicyServer(engine, host=str(serve_cfg.host), port=int(serve_cfg.port))
    print(f"Serving {sorted(engine.models())} on {server.address} (SIGTERM drains and exits)")
    server.serve_forever()


def main(args: Optional[Sequence[str]] = None) -> None:
    argv = list(args) if args is not None else sys.argv[1:]
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return
    command, rest = argv[0], argv[1:]
    if command == "export":
        _export(rest)
    elif command == "serve":
        _serve(rest)
    else:
        raise SystemExit(f"Unknown command {command!r}; expected 'export' or 'serve'.\n{__doc__}")
