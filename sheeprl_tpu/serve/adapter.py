"""Shared machinery for per-algorithm policy adapters.

An adapter is the algorithm-specific half of the serving stack: it knows how
to distill a training checkpoint into inference params (class method
``export``), how to rebuild the apply path from an artifact spec (the
constructor), and how to turn client observation rows into the batched,
padded arrays the engine's jitted apply consumes. The engine sees only this
interface — request queueing, bucketing, jit/donation, and telemetry live
there; everything that mentions an agent class lives in
``sheeprl_tpu/algos/<algo>/serve.py``.

Apply contract (what ``make_apply(greedy)`` must return)::

    apply(params, obs, seeds, state) -> (actions, new_state)

- ``obs``: the pytree ``pack_rows`` produced, leading dim = bucket size B;
- ``seeds``: uint32 [B] — per-row PRNG seeds for keyed-stochastic modes
  (ignored by purely-greedy stateless paths);
- ``state``: None for stateless policies; for stateful ones the per-session
  state rows stacked on a new leading axis [B, ...] (``new_session`` creates
  one row). The engine donates ``state`` (or ``obs`` when stateless) to the
  jit, so apply must not alias its input buffers into the output.

The leading dim is static at trace time (the engine compiles one graph per
power-of-two bucket), so adapters may branch on ``B == 1`` in python to keep
the single-request graph identical to the algorithm's ``evaluate`` path —
that is what makes the round-trip bit-identity tests possible.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Tuple

import numpy as np

from sheeprl_tpu.serve.artifact import spec_to_space
from sheeprl_tpu.utils.utils import dotdict


class PolicyAdapterBase:
    stateful = False

    def __init__(self, spec: Dict[str, Any], params: Any) -> None:
        import jax

        from sheeprl_tpu.core.precision import resolve_precision

        self.spec = spec
        self.cfg = dotdict(spec["config"])
        self.obs_space = spec_to_space(spec["observation_space"])
        self.action_space = spec_to_space(spec["action_space"])
        self.compute_dtype = resolve_precision(
            str(self.cfg.get("precision", "32-true"))
        ).compute_dtype
        # One H2D transfer at load; every batch reuses the device copy.
        self.params = jax.device_put(params)

    # ------------------------------------------------------------ row layout
    @property
    def mlp_keys(self) -> Tuple[str, ...]:
        return tuple(self.cfg.algo.mlp_keys.encoder)

    @property
    def cnn_keys(self) -> Tuple[str, ...]:
        cnn = self.cfg.algo.get("cnn_keys")
        return tuple(cnn.encoder) if cnn else ()

    def row_spec(self) -> Dict[str, Tuple[Tuple[int, ...], str]]:
        """Per-request observation layout: key -> (shape, dtype). Vector keys
        are flattened (prepare_obs parity); pixel keys keep HWC layout and
        their space dtype (normalization happens in-graph)."""
        layout: Dict[str, Tuple[Tuple[int, ...], str]] = {}
        for k in self.cnn_keys:
            sp = self.obs_space[k]
            layout[k] = (tuple(sp.shape), np.dtype(sp.dtype).name)
        for k in self.mlp_keys:
            sp = self.obs_space[k]
            layout[k] = ((int(prod(sp.shape)),), "float32")
        return layout

    def normalize_row(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Validate/coerce one client obs row against the artifact's spec.
        Raises ValueError (the server's 400) on missing keys or wrong sizes."""
        if not isinstance(obs, dict):
            raise ValueError(f"obs must be a dict of observation keys, got {type(obs).__name__}")
        row: Dict[str, np.ndarray] = {}
        for k, (shape, dtype) in self.row_spec().items():
            if k not in obs:
                raise ValueError(f"obs is missing key {k!r} (expected keys: {sorted(self.row_spec())})")
            arr = np.asarray(obs[k])
            if int(arr.size) != int(prod(shape)):
                raise ValueError(
                    f"obs[{k!r}] has {arr.size} elements, expected {int(prod(shape))} (shape {shape})"
                )
            row[k] = np.ascontiguousarray(arr.reshape(shape).astype(dtype, copy=False))
        return row

    def pack_rows(self, rows: List[Dict[str, np.ndarray]], batch: int) -> Any:
        """Stack ``rows`` (already normalized) into [batch, ...] arrays,
        zero-padding past ``len(rows)``. Default: dict-obs layout."""
        packed: Dict[str, np.ndarray] = {}
        for k, (shape, dtype) in self.row_spec().items():
            out = np.zeros((batch, *shape), dtype)
            for i, row in enumerate(rows):
                out[i] = row[k]
            packed[k] = out
        return packed

    # -------------------------------------------------------------- sessions
    def new_session(self, seed: int) -> Any:  # pragma: no cover - stateless default
        raise TypeError(f"{type(self).__name__} is stateless and has no sessions")

    # ----------------------------------------------------------------- apply
    def make_apply(self, greedy: bool):
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        """Model card for /v1/models."""
        return {
            "algo": self.spec["algo"],
            "stateful": self.stateful,
            "policy_step": self.spec.get("policy_step"),
            "env_id": self.spec.get("env_id"),
            "obs_keys": {k: list(v[0]) for k, v in self.row_spec().items()},
            "action_space": self.spec["action_space"],
        }


def extract_policy_config(cfg) -> Dict[str, Any]:
    """The config subtree an artifact carries: everything an adapter's module
    rebuild reads, nothing from the training side (buffers, optimizers,
    checkpoints). ``algo`` is taken whole — module hyper-parameters live all
    over that subtree and cherry-picking them per algorithm is how specs rot."""
    algo = cfg.algo.as_dict() if hasattr(cfg.algo, "as_dict") else dict(cfg.algo)
    dist = cfg.get("distribution") or {"type": "auto"}
    return {
        "algo": algo,
        "distribution": dist.as_dict() if hasattr(dist, "as_dict") else dict(dist),
        "env": {"screen_size": cfg.env.get("screen_size", 64)},
        "precision": str(cfg.fabric.get("precision", "32-true")),
    }


def inference_runtime(precision):
    """Minimal stand-in for the training Runtime, satisfying what the algo
    ``build_agent`` factories read (precision policy + an init key — unused
    when every param tree is restored, but the factories split it anyway)."""
    import types

    import jax

    return types.SimpleNamespace(precision=precision, root_key=jax.random.PRNGKey(0))


def seeds_to_keys(seeds):
    """uint32 [B] seeds -> stacked PRNG keys [B, 2], in-graph (the same
    ``jax.random.PRNGKey`` the evaluate paths derive their keys from)."""
    import jax

    return jax.vmap(jax.random.PRNGKey)(seeds)
