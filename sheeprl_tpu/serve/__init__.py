"""sheeprl_tpu.serve: policy artifact export + dynamic-batching inference.

Layers (each usable on its own):

- :mod:`~sheeprl_tpu.serve.artifact` — export/load self-contained, versioned
  policy artifacts (params + apply spec + space/preprocessing metadata,
  digest-verified, written atomically);
- :mod:`~sheeprl_tpu.serve.engine` — the dynamic micro-batching
  :class:`InferenceEngine` (bounded queue, power-of-two buckets, one donated
  jitted apply per batch, warm-up at load, LRU multi-model hosting);
- :mod:`~sheeprl_tpu.serve.server` — stdlib HTTP front end (``/v1/act``,
  ``/v1/models``, ``/healthz``) with deadline-based shedding and graceful
  SIGTERM drain, plus the in-process :class:`ServeClient`;
- :mod:`~sheeprl_tpu.serve.adapter` / per-algorithm
  ``sheeprl_tpu/algos/<algo>/serve.py`` — the policy adapters.
"""

from sheeprl_tpu.serve.artifact import (
    PolicyArtifact,
    export_artifact,
    load_artifact,
    make_policy,
    read_artifact_manifest,
    validate_artifact,
)
from sheeprl_tpu.serve.engine import (
    EngineClosed,
    EngineOverloaded,
    InferenceEngine,
    RequestExpired,
    next_pow2,
)
from sheeprl_tpu.serve.registry import get_policy_cls, register_all_policies, register_policy
from sheeprl_tpu.serve.server import PolicyServer, ServeClient

__all__ = [
    "EngineClosed",
    "EngineOverloaded",
    "InferenceEngine",
    "PolicyArtifact",
    "PolicyServer",
    "RequestExpired",
    "ServeClient",
    "export_artifact",
    "get_policy_cls",
    "load_artifact",
    "make_policy",
    "next_pow2",
    "read_artifact_manifest",
    "register_all_policies",
    "register_policy",
    "validate_artifact",
]
