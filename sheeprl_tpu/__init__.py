"""sheeprl-tpu: a TPU-native deep reinforcement learning framework.

Capability parity with SheepRL (PyTorch + Lightning Fabric), re-designed for
TPU: JAX/XLA compute graphs, pjit/shard_map data- and model-parallelism over a
device mesh, Pallas kernels for the RSSM hot loop, host-side numpy replay
buffers with async infeed, and a native YAML config composition engine.
"""

from __future__ import annotations

__version__ = "0.1.0"

import os

# Import every algorithm module so their @register_algorithm decorators run
# (parity with the reference's sheeprl/__init__.py:18-47 registration scheme).
# Kept lazy-safe: a broken optional dependency in one algo must not break the
# others, so each import is individually guarded.
_ALGO_MODULES = [
    "sheeprl_tpu.algos.ppo.ppo",
    "sheeprl_tpu.algos.ppo.ppo_decoupled",
    "sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent",
    "sheeprl_tpu.algos.a2c.a2c",
    "sheeprl_tpu.algos.sac.sac",
    "sheeprl_tpu.algos.sac.sac_decoupled",
    "sheeprl_tpu.algos.sac_ae.sac_ae",
    "sheeprl_tpu.algos.droq.droq",
    "sheeprl_tpu.algos.dreamer_v1.dreamer_v1",
    "sheeprl_tpu.algos.dreamer_v2.dreamer_v2",
    "sheeprl_tpu.algos.dreamer_v3.dreamer_v3",
    "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration",
    "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_finetuning",
    "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration",
    "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_finetuning",
    "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration",
    "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_finetuning",
]

_EVAL_MODULES = [m.rsplit(".", 1)[0] + ".evaluate" for m in _ALGO_MODULES]


def register_all() -> None:
    """Import all algorithm + evaluation modules, populating the registries.

    A module that is absent from disk (not yet built / optional) is skipped
    silently; a module that EXISTS but fails to import is a real bug (a broken
    refactor would otherwise surface later as "unknown algorithm"), so it
    warns — or raises under SHEEPRL_TPU_STRICT_IMPORTS=1.
    """
    import importlib
    import importlib.util
    import warnings

    for mod in _ALGO_MODULES + _EVAL_MODULES:
        try:
            importlib.import_module(mod)
        except ImportError as e:
            if os.environ.get("SHEEPRL_TPU_STRICT_IMPORTS", "0") == "1":
                raise
            try:
                on_disk = importlib.util.find_spec(mod) is not None
            except ModuleNotFoundError:
                on_disk = False
            if on_disk:
                warnings.warn(
                    f"algorithm module '{mod}' exists but failed to import ({e!r}); "
                    "its algorithms will be unavailable",
                    ImportWarning,
                )
