"""CLI dispatcher: config composition → registry lookup → Runtime → algorithm.

Parity with the reference CLI (sheeprl/cli.py:23-450): `run` composes the
config (native composition engine instead of Hydra), handles resume-config
merging, prunes metric/model-manager keys against the algorithm's declared
sets, instantiates the substrate (Runtime instead of Fabric), seeds, and
invokes the registered entrypoint. `evaluation` rebuilds a single-device
runtime from a checkpoint's saved config and calls the registered eval fn.
"""

from __future__ import annotations

import importlib
import os
import pathlib
import sys
import warnings
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.config.loader import compose
from sheeprl_tpu.registry import algorithm_registry, evaluation_registry
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import dotdict, print_config


def _load_ckpt_config(ckpt_path: pathlib.Path) -> dotdict:
    """Load the config.yaml saved next to a run's checkpoint directory."""
    import yaml

    with open(ckpt_path.parent.parent / "config.yaml") as fp:
        return dotdict(yaml.safe_load(fp))


def resume_from_checkpoint(cfg: dotdict) -> dotdict:
    """Force-merge the original run's config.yaml, keeping the new run's
    total_steps/paths (reference: cli.py:23-57)."""
    ckpt_path = pathlib.Path(cfg.checkpoint.resume_from)
    old_cfg = _load_ckpt_config(ckpt_path)
    if old_cfg.env.id != cfg.env.id:
        raise ValueError(
            "This experiment is run with a different environment from the one of the experiment you want to restart. "
            f"Got '{cfg.env.id}', but the environment of the experiment of the checkpoint was {old_cfg.env.id}. "
            "Set properly the environment for restarting the experiment."
        )
    if old_cfg.algo.name != cfg.algo.name:
        raise ValueError(
            "This experiment is run with a different algorithm from the one of the experiment you want to restart. "
            f"Got '{cfg.algo.name}', but the algorithm of the experiment of the checkpoint was {old_cfg.algo.name}. "
            "Set properly the algorithm name for restarting the experiment."
        )
    if old_cfg.algo.get("learning_starts", 0) > 0:
        warnings.warn(
            "The `algo.learning_starts` parameter is greater than zero. "
            "This means that the resuming experiment will pre-fill the buffer for `algo.learning_starts` steps. "
            "If this is not intended please set the `algo.learning_starts=0` parameter in the experiment "
            "configuration or through the CLI."
        )
    old = old_cfg.as_dict()
    old.pop("root_dir", None)
    old.pop("run_name", None)
    old.get("algo", {}).pop("total_steps", None)
    old.get("algo", {}).pop("learning_starts", None)
    old.get("checkpoint", {}).pop("resume_from", None)
    # Chaos injectors are one-shot experiment artifacts: re-inheriting them
    # from the preempted run's config would replay the same fault right after
    # resume (a SIGTERM-at-step-N injector becomes a preemption loop). The
    # resuming invocation's own chaos config stays authoritative.
    old.get("resilience", {}).pop("chaos", None)

    def merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                merge(dst[k], v)
            else:
                dst[k] = v

    merged = cfg.as_dict()
    merge(merged, old)
    return dotdict(merged)


def check_configs(cfg: dotdict) -> None:
    """Imperative config validation (reference: cli.py:271-345, minus the
    DDP-strategy matrix that has no JAX counterpart)."""
    if cfg.algo.name not in algorithm_registry:
        raise RuntimeError(
            f"Given the algorithm named '{cfg.algo.name}', no entrypoint has been registered. "
            f"Available: {sorted(algorithm_registry)}"
        )
    accelerator = str(cfg.fabric.get("accelerator", "auto")).lower()
    if accelerator not in ("auto", "cpu", "tpu", "axon"):
        raise ValueError(f"Unknown fabric.accelerator '{accelerator}'. Valid: auto | cpu | tpu | axon")
    player_device = str(cfg.fabric.get("player_device", "auto") or "auto").lower()
    if player_device not in ("auto", "host", "mesh"):
        raise ValueError(f"Unknown fabric.player_device '{player_device}'. Valid: auto | host | mesh")
    player_sync = str(cfg.fabric.get("player_sync", "fresh") or "fresh").lower()
    if player_sync not in ("fresh", "async"):
        raise ValueError(f"Unknown fabric.player_sync '{player_sync}'. Valid: fresh | async")
    tele = cfg.get("telemetry")
    if tele is not None and tele.get("profiler") is not None:
        start = int(tele.profiler.get("start_step", -1))
        stop = int(tele.profiler.get("stop_step", -1))
        if (start >= 0) != (stop >= 0) or (start >= 0 and stop <= start):
            raise ValueError(
                "telemetry.profiler window must satisfy 0 <= start_step < stop_step "
                f"(or both -1 to disable); got [{start}, {stop})"
            )
    res = cfg.get("resilience")
    if res is not None:
        wd = res.get("watchdog")
        if wd is not None:
            on_trip = str(wd.get("on_trip", "warn") or "warn").lower()
            if on_trip not in ("warn", "preempt", "abort"):
                raise ValueError(
                    f"Unknown resilience.watchdog.on_trip '{on_trip}'. Valid: warn | preempt | abort"
                )
            if bool(wd.get("enabled", False)) and float(wd.get("timeout_s", 120.0) or 0.0) <= 0:
                raise ValueError("resilience.watchdog.enabled=True requires timeout_s > 0")
        ch = res.get("chaos")
        if ch is not None and bool(ch.get("enabled", False)):
            from sheeprl_tpu.core.chaos import STEP_INJECTOR_KINDS

            known = ("env_step_raise", "nan_reward") + tuple(STEP_INJECTOR_KINDS)
            for inj in ch.get("injectors") or []:
                if str(inj.get("kind", "")) not in known:
                    raise ValueError(
                        f"Unknown resilience.chaos injector kind {inj.get('kind')!r}. Valid: {known}"
                    )
    fleet = cfg.get("fleet")
    if fleet is not None:
        replicas = int(fleet.get("replicas", 1) or 1)
        if replicas < 1:
            raise ValueError(f"fleet.replicas must be >= 1, got {replicas}")
        quorum = int(fleet.get("quorum", 1) or 1)
        if not 1 <= quorum <= replicas:
            raise ValueError(f"fleet.quorum must be in [1, fleet.replicas={replicas}], got {quorum}")
        start_method = str(fleet.get("start_method", "spawn") or "spawn")
        if start_method != "spawn":
            # Forking after JAX initializes inherits locked runtime state in
            # every replica; only spawn gives each one a clean interpreter.
            raise ValueError(f"fleet.start_method must be 'spawn', got {start_method!r}")
        from sheeprl_tpu.core.fleet import fleet_active

        if fleet_active(cfg) and not str(cfg.algo.name).endswith("_decoupled"):
            raise ValueError(
                "Fleet mode (fleet.replicas > 1 or fleet.enabled=True) requires a decoupled "
                f"algorithm (the replicas own the envs); got algo.name={cfg.algo.name!r}"
            )
    health = cfg.get("health")
    if health is not None:
        for knob in ("policy", "anomaly_policy"):
            value = str(health.get(knob, "warn") or "warn").lower()
            if value not in ("warn", "preempt", "abort"):
                raise ValueError(f"Unknown health.{knob} '{value}'. Valid: warn | preempt | abort")
        ewma = health.get("ewma")
        if ewma is not None:
            alpha = float(ewma.get("alpha", 0.1) or 0.0)
            if not 0.0 < alpha <= 1.0:
                raise ValueError(f"health.ewma.alpha must be in (0, 1], got {alpha}")
            if float(ewma.get("k", 6.0) or 0.0) <= 0.0:
                raise ValueError("health.ewma.k must be > 0")
        if bool(health.get("enabled", False)) and int(cfg.metric.get("log_level", 1)) <= 0:
            warnings.warn(
                "health.enabled=True but metric.log_level=0: sentinels observe at the metric "
                "log cadence, so nothing will be watched. Set metric.log_level >= 1.",
            )
    # Anakin lane (core/fused_loop.py): fused rollout+train needs a pure-JAX
    # env and an algorithm with a fused driver.
    if bool(cfg.algo.get("fused_rollout", False)):
        if not bool(cfg.env.get("jax_native", False)):
            raise ValueError(
                "algo.fused_rollout=True requires env.jax_native=True: the fused superstep "
                "steps the env inside the training jit, so it must be a pure-JAX env "
                "(sheeprl_tpu/envs/jax — e.g. env=jax_cartpole, env=jax_pendulum)."
            )
        if cfg.algo.name not in ("ppo", "sac", "dreamer_v3"):
            raise ValueError(
                f"algo.fused_rollout is implemented for ppo, sac and dreamer_v3; got '{cfg.algo.name}'. "
                "Run this algorithm on a jax env through the host lane (env.jax_native with "
                "algo.fused_rollout=false uses the JaxToGymnasium wrapper) instead."
            )
        if int(cfg.algo.get("fused_superstep_steps", 64)) < 1:
            raise ValueError("algo.fused_superstep_steps must be >= 1")
    if bool(cfg.env.get("jax_native", False)):
        from sheeprl_tpu.envs.jax import make_jax_env

        try:
            make_jax_env(cfg.env.id)
        except ValueError as err:
            raise ValueError(f"env.jax_native=True but env.id is not a registered jax env: {err}") from err
    entry = algorithm_registry[cfg.algo.name]
    if (
        entry.decoupled
        and player_device == "mesh"
        and int(os.environ.get("SHEEPRL_NUM_PROCS", "1")) < 2
        and cfg.fabric.get("devices", 1) in (1, "1")
    ):
        # player_device=host always works on one device (the full mesh
        # trains); =auto is resolved at runtime and may pick host, so only
        # the explicit on-mesh split is rejected here — auto that resolves
        # to mesh fails later in split_player_trainer with the same message.
        raise RuntimeError(
            f"The decoupled algorithm '{cfg.algo.name}' requires at least 2 devices/processes "
            "(one player + at least one trainer), or fabric.player_device=host to run the "
            "player on the host CPU and train on every device."
        )


def _prune_metric_and_model_keys(cfg: dotdict, utils_module) -> None:
    """Keep only the metric/model keys the algorithm declares
    (reference: cli.py:151-181)."""
    if cfg.get("metric") is not None:
        predefined = set()
        if not hasattr(utils_module, "AGGREGATOR_KEYS"):
            warnings.warn(
                f"No 'AGGREGATOR_KEYS' set found for the {cfg.algo.name} algorithm. No metric will be logged.",
                UserWarning,
            )
        else:
            predefined = utils_module.AGGREGATOR_KEYS
        timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer
        for k in set(cfg.metric.aggregator.metrics.keys()) - predefined:
            cfg.metric.aggregator.metrics.pop(k, None)
        MetricAggregator.disabled = cfg.metric.log_level == 0 or len(cfg.metric.aggregator.metrics) == 0

    if cfg.get("model_manager") is not None and not cfg.model_manager.disabled:
        _prune_model_keys(cfg, utils_module)


def _prune_model_keys(cfg: dotdict, utils_module) -> None:
    """Drop model-manager entries the algorithm does not checkpoint; warn and
    disable when nothing remains."""
    predefined = set()
    if not hasattr(utils_module, "MODELS_TO_REGISTER"):
        warnings.warn(
            f"No 'MODELS_TO_REGISTER' set found for the {cfg.algo.name} algorithm. "
            "No model will be registered.",
            UserWarning,
        )
    else:
        predefined = utils_module.MODELS_TO_REGISTER
    for k in set(cfg.model_manager.models.keys()) - predefined:
        cfg.model_manager.models.pop(k, None)
    if len(cfg.model_manager.models) == 0:
        warnings.warn(
            f"No model-manager entries match the '{cfg.algo.name}' algorithm's registered-model "
            f"contract ({sorted(predefined)}); model registration is disabled.",
            UserWarning,
        )
        cfg.model_manager.disabled = True


def run_algorithm(cfg: dotdict) -> None:
    """Registry lookup + Runtime construction + entrypoint call
    (reference: cli.py:60-199; fabric.launch collapses to a plain call —
    JAX multi-host processes are launched externally, one per host)."""
    entry = algorithm_registry[cfg.algo.name]
    task = importlib.import_module(entry.module)
    utils_module = importlib.import_module(entry.module.rsplit(".", 1)[0] + ".utils")
    command = task.__dict__[entry.entrypoint.__name__]

    _prune_metric_and_model_keys(cfg, utils_module)

    kwargs = {}
    if "finetuning" in cfg.algo.name and "p2e" in entry.module:
        # P2E chaining: the finetuning phase inherits the exploration run's
        # environment setup from the checkpoint's saved config
        # (reference: cli.py:117-148).
        expl_ckpt = cfg.checkpoint.get("exploration_ckpt_path")
        if not expl_ckpt or str(expl_ckpt) == "???":
            raise ValueError(
                "P2E finetuning needs the exploration phase's checkpoint: set "
                "'checkpoint.exploration_ckpt_path=<path-to-exploration-ckpt>'."
            )
        ckpt_path = pathlib.Path(expl_ckpt)
        exploration_cfg = _load_ckpt_config(ckpt_path)
        if exploration_cfg.env.id != cfg.env.id:
            raise ValueError(
                "This experiment is run with a different environment from "
                "the one of the exploration you want to finetune. "
                f"Got '{cfg.env.id}', but the environment used during exploration "
                f"was {exploration_cfg.env.id}. "
                "Set properly the environment for finetuning the experiment."
            )
        kwargs["exploration_cfg"] = exploration_cfg
        for env_key in (
            "frame_stack",
            "screen_size",
            "action_repeat",
            "grayscale",
            "clip_rewards",
            "frame_stack_dilation",
            "max_episode_steps",
            "reward_as_observation",
        ):
            cfg.env[env_key] = exploration_cfg.env[env_key]
        _env_target = str(cfg.env.wrapper.get("_target_", "")).lower()
        if "minerl" in _env_target or "minedojo" in _env_target:
            for env_key in (
                "max_pitch",
                "min_pitch",
                "sticky_jump",
                "sticky_attack",
                "break_speed_multiplier",
            ):
                cfg.env[env_key] = exploration_cfg.env[env_key]
        if cfg.buffer.load_from_exploration:
            cfg.fabric.devices = exploration_cfg.fabric.devices
            cfg.fabric.num_nodes = exploration_cfg.fabric.num_nodes

    if cfg.get("xla_deterministic"):
        # Reference: the reproducible() wrapper around every entrypoint
        # (sheeprl/cli.py:187-197). Must precede launch(): XLA_FLAGS are
        # read when the backend is constructed.
        from sheeprl_tpu.core.runtime import enable_xla_determinism

        enable_xla_determinism()
    runtime = instantiate(cfg.fabric)
    runtime.launch()
    runtime.seed_everything(cfg.seed)
    # The run's observability surface: every algorithm opens it against its
    # log dir and threads it through the train loop (howto/observability.md).
    from sheeprl_tpu.telemetry import Telemetry

    runtime.telemetry = Telemetry.from_config(cfg)
    # The run's fault-tolerance surface: preemption guard + env supervisor +
    # dispatch watchdog + chaos injectors (howto/fault_tolerance.md).
    from sheeprl_tpu.core.resilience import Resilience

    runtime.resilience = Resilience.from_config(cfg)
    # The run's training-health sentinels: in-jit probes + host anomaly
    # detection with warn|preempt|abort escalation (howto/observability.md).
    from sheeprl_tpu.telemetry.health import HealthMonitor

    runtime.health = HealthMonitor.from_config(cfg)
    import jax

    # Eager ops and un-sharded jits must land on the chosen accelerator (the
    # host may pin a different default backend, e.g. a tunneled TPU while the
    # config selects cpu or vice versa).
    with jax.default_device(runtime.device):
        command(runtime, cfg, **kwargs)


def run(args: Optional[Sequence[str]] = None) -> None:
    """Training entry: `python -m sheeprl_tpu exp=... [overrides...]`
    (reference: cli.run, cli.py:358-366)."""
    import sheeprl_tpu

    sheeprl_tpu.register_all()
    overrides = list(args) if args is not None else sys.argv[1:]
    cfg = compose("config", overrides)
    os.environ.setdefault("OMP_NUM_THREADS", str(cfg.get("num_threads", 1)))
    if str(cfg.checkpoint.resume_from or "").startswith("auto"):
        # `checkpoint.resume_from=auto[:<dir>]` — follow the preemption
        # guard's autoresume.json pointer, or fall back to the newest
        # manifest-valid checkpoint under the search root (skipping torn or
        # corrupt saves). See howto/fault_tolerance.md.
        from sheeprl_tpu.core.resilience import resolve_auto_resume

        resolved = resolve_auto_resume(str(cfg.checkpoint.resume_from), cfg.get("log_root"))
        if resolved is None:
            raise FileNotFoundError(
                f"checkpoint.resume_from={cfg.checkpoint.resume_from!r}: no valid checkpoint "
                "found (no autoresume.json pointer and no manifest-valid ckpt_*.ckpt)"
            )
        print(f"Auto-resume: resolved {cfg.checkpoint.resume_from!r} -> {resolved}")
        cfg.checkpoint.resume_from = resolved
    if cfg.checkpoint.resume_from:
        cfg = resume_from_checkpoint(cfg)
    if cfg.metric.log_level > 0:
        print_config(cfg)
    check_configs(cfg)
    run_algorithm(cfg)


def registration(args: Optional[Sequence[str]] = None) -> None:
    """Model-registration entry: `python -m sheeprl_tpu.registration
    checkpoint_path=<ckpt> model_manager=<algo> [overrides...]` — logs the
    checkpoint's models to MLflow and registers the ones selected by the
    model_manager config (reference: cli.registration, cli.py:408-450)."""
    import sheeprl_tpu

    sheeprl_tpu.register_all()
    overrides = list(args) if args is not None else sys.argv[1:]
    ckpt_override = [o for o in overrides if o.startswith("checkpoint_path=")]
    if not ckpt_override:
        raise ValueError("You must specify checkpoint_path=<path-to-checkpoint>")
    checkpoint_path = pathlib.Path(ckpt_override[-1].split("=", 1)[1])
    ckpt_cfg = _load_ckpt_config(checkpoint_path)

    # The model_manager configs interpolate ${exp_name}/${env.id}: supply them
    # from the checkpoint's run identity before composing.
    cfg = compose(
        "model_manager_config",
        overrides + [f"+exp_name={ckpt_cfg.exp_name}", f"+env.id={ckpt_cfg.env.id}"],
    )
    # Inherit the rest of the run's identity from the checkpoint's config
    for key in ("env", "algo", "distribution", "seed"):
        cfg[key] = ckpt_cfg[key]
    cfg.to_log = ckpt_cfg

    # The models to register are the algorithm's registered-model contract
    entry = algorithm_registry.get(cfg.algo.name)
    if entry is None:
        raise RuntimeError(f"Unknown algorithm '{cfg.algo.name}' in the checkpoint config")
    utils_module = importlib.import_module(entry.module.rsplit(".", 1)[0] + ".utils")
    models_keys = sorted(getattr(utils_module, "MODELS_TO_REGISTER", set()))
    cfg.model_manager.disabled = False
    _prune_model_keys(cfg, utils_module)

    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    from sheeprl_tpu.utils.mlflow import register_model_from_checkpoint

    state = load_checkpoint(str(checkpoint_path))
    runtime = instantiate(
        dotdict(
            {
                "_target_": "sheeprl_tpu.core.runtime.Runtime",
                "devices": 1,
                "accelerator": "cpu",
                "precision": str(ckpt_cfg.fabric.get("precision", "32-true")),
            }
        )
    )
    runtime.launch()
    runtime.seed_everything(cfg.seed)
    register_model_from_checkpoint(runtime, cfg, state, models_keys)


def evaluation(args: Optional[Sequence[str]] = None) -> None:
    """Evaluation entry: `python -m sheeprl_tpu.eval checkpoint_path=... [overrides]`
    (reference: cli.evaluation, cli.py:369-405 + eval_algorithm 202-268)."""
    import yaml

    import sheeprl_tpu

    sheeprl_tpu.register_all()
    overrides = list(args) if args is not None else sys.argv[1:]
    ckpt_override = [o for o in overrides if o.startswith("checkpoint_path=")]
    if not ckpt_override:
        raise ValueError("You must specify checkpoint_path=<path-to-checkpoint>")
    checkpoint_path = pathlib.Path(ckpt_override[-1].split("=", 1)[1])
    rest: List[str] = [o for o in overrides if not o.startswith("checkpoint_path=")]

    with open(checkpoint_path.parent.parent / "config.yaml") as fp:
        ckpt_cfg = dotdict(yaml.safe_load(fp))

    # Start from the run's config, let CLI overrides win, force eval-time keys.
    from sheeprl_tpu.config.loader import _parse_value
    from sheeprl_tpu.utils.utils import set_by_path

    cfg = ckpt_cfg
    user_keys = set()
    for ov in rest:
        k, v = ov.split("=", 1)
        k = k.lstrip("+")
        user_keys.add(k)
        set_by_path(cfg, k, _parse_value(v))
    # <run_name>/<version_N>/evaluation next to the original run
    # (reference: cli.py:393-401 — root_dir becomes the absolute run root).
    cfg.root_dir = str(checkpoint_path.parent.parent.parent.parent)
    cfg.run_name = str(
        os.path.join(
            os.path.basename(checkpoint_path.parent.parent.parent),
            os.path.basename(checkpoint_path.parent.parent),
            "evaluation",
        )
    )
    cfg.checkpoint.resume_from = str(checkpoint_path)
    # Eval-time defaults (single env, single local device) apply only where
    # the user did not explicitly override: `env.num_envs=4` or `fabric.*`
    # on the command line must survive this block, not be clobbered by it.
    if "env.num_envs" not in user_keys:
        cfg.env.num_envs = 1
    user_fabric_keys = {k.split(".", 1)[1] for k in user_keys if k.startswith("fabric.")}
    eval_fabric = dotdict(
        {
            "_target_": cfg.fabric.get("_target_", "sheeprl_tpu.core.runtime.Runtime"),
            "devices": 1,
            "num_nodes": 1,
            "strategy": "single_device",
            "accelerator": cfg.fabric.get("accelerator", "auto"),
            "precision": cfg.fabric.get("precision", "32-true"),
            "model_axis": 1,
        }
    )
    dropped = []
    for key in sorted(user_fabric_keys):
        if key in cfg.fabric:
            eval_fabric[key] = cfg.fabric[key]
        else:
            dropped.append(f"fabric.{key}")
    if dropped:
        warnings.warn(
            f"Evaluation ignores unknown fabric overrides: {', '.join(dropped)}",
            stacklevel=2,
        )
    cfg.fabric = eval_fabric

    if cfg.algo.name not in evaluation_registry:
        raise RuntimeError(
            f"Given the algorithm named '{cfg.algo.name}', no evaluation entrypoint has been registered. "
            f"Available: {sorted(evaluation_registry)}"
        )
    entry = evaluation_registry[cfg.algo.name]
    task = importlib.import_module(entry.module)
    command = task.__dict__[entry.entrypoint.__name__]

    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    state = load_checkpoint(str(checkpoint_path))

    runtime = instantiate(cfg.fabric)
    runtime.launch()
    runtime.seed_everything(cfg.seed)
    command(runtime, cfg, state)
