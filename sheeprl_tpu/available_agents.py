"""Print every registered algorithm and its evaluation entrypoint
(reference: sheeprl/available_agents.py; `python -m sheeprl_tpu.available_agents`)."""

from __future__ import annotations

from rich.console import Console
from rich.table import Table

import sheeprl_tpu
from sheeprl_tpu.registry import algorithm_registry, evaluation_registry


def available_agents() -> None:
    sheeprl_tpu.register_all()
    table = Table(title="sheeprl-tpu Agents")
    table.add_column("Module")
    table.add_column("Algorithm")
    table.add_column("Entrypoint")
    table.add_column("Decoupled")
    table.add_column("Evaluated by")

    for name in sorted(algorithm_registry):
        entry = algorithm_registry[name]
        evaluated_by = "Undefined"
        if name in evaluation_registry:
            ev = evaluation_registry[name]
            evaluated_by = f"{ev.module}.{ev.entrypoint.__name__}"
        table.add_row(
            entry.module,
            entry.name,
            entry.entrypoint.__name__,
            str(entry.decoupled),
            evaluated_by,
        )

    Console().print(table)


# Console-script entry (pyproject: sheeprl-tpu-agents)
main = available_agents


if __name__ == "__main__":
    available_agents()
