"""`python -m sheeprl_tpu.registration` → model-registration CLI
(reference console script `sheeprl-registration`)."""

from sheeprl_tpu.cli import registration

if __name__ == "__main__":
    registration()
