from sheeprl_tpu.config.instantiate import instantiate, locate
from sheeprl_tpu.config.loader import MISSING, ConfigError, Composer, compose, default_config_dir, search_paths

__all__ = [
    "MISSING",
    "ConfigError",
    "Composer",
    "compose",
    "default_config_dir",
    "search_paths",
    "instantiate",
    "locate",
]
