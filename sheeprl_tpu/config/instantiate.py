"""`_target_`-driven object instantiation.

Parity with `hydra.utils.instantiate` as the reference uses it (optimizers,
env wrappers, metric objects, e.g. sheeprl/cli.py:101,149, ppo.py:184,199):
a config node with a ``_target_`` key names a callable by dotted path; the
remaining keys are its kwargs. ``_partial_: true`` returns a functools.partial
instead of calling.
"""

from __future__ import annotations

import functools
import importlib
from typing import Any, Mapping


def locate(path: str) -> Any:
    """Import a dotted path to an object (module.attr[.attr...])."""
    parts = path.split(".")
    for split in range(len(parts) - 1, 0, -1):
        module_name = ".".join(parts[:split])
        try:
            module = importlib.import_module(module_name)
        except ModuleNotFoundError as e:
            # Only swallow "this prefix isn't a module"; anything else — a
            # transitive missing dependency, or a module that exists but
            # raises a bare ImportError("install the X extra") — is a real
            # error the user must see, so plain ImportError propagates.
            if e.name is not None and not (module_name == e.name or module_name.startswith(e.name + ".")):
                raise
            continue
        obj = module
        try:
            for attr in parts[split:]:
                obj = getattr(obj, attr)
        except AttributeError:
            continue
        return obj
    raise ImportError(f"Cannot locate object at dotted path: '{path}'")


def _instantiate_children(value: Any) -> Any:
    """Recursively instantiate ``_target_`` nodes anywhere in a config subtree
    (full-recursive semantics, like hydra.utils.instantiate's default)."""
    if isinstance(value, Mapping):
        if "_target_" in value:
            return instantiate(value)
        return {k: _instantiate_children(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_instantiate_children(v) for v in value)
    return value


def instantiate(node: Any, *args: Any, **overrides: Any) -> Any:
    """Instantiate a ``_target_`` config node (recursively for nested nodes)."""
    if isinstance(node, Mapping) and "_target_" in node:
        kwargs = {}
        partial = False
        target = None
        for k, v in node.items():
            if k == "_target_":
                target = v
            elif k == "_partial_":
                partial = bool(v)
            elif k.startswith("_"):
                continue
            else:
                kwargs[k] = _instantiate_children(v)
        kwargs.update(overrides)
        fn = locate(target)
        if partial:
            return functools.partial(fn, *args, **kwargs)
        return fn(*args, **kwargs)
    if overrides or args:
        raise ValueError("Cannot pass args/kwargs when instantiating a non-_target_ node")
    return node
