"""Hydra-lite YAML config composition.

The reference framework composes its runtime config with Hydra 1.3
(sheeprl/configs/config.yaml + ~100 group files, search-path plugin in
hydra_plugins/sheeprl_search_path.py). Hydra is not available in this
environment, and a full dependency on it is unnecessary: this module
implements the subset of composition semantics the framework needs, natively:

- a root config with a ``defaults`` list,
- config groups (``algo/``, ``env/``, ``exp/``, ...) selected as
  ``- group: option`` entries or CLI ``group=option`` overrides,
- ``_self_`` ordering, same-group includes (``- dreamer_v3``),
- ``override /group: option`` directives (used heavily by ``exp/`` files),
- package targeting: ``# @package _global_`` headers and ``@pkg`` suffixes
  (e.g. ``/optim@world_model.optimizer: adam``),
- ``${a.b.c}`` interpolation with ``${now:...}`` resolver,
- dotted CLI value overrides (``algo.gamma=0.9``) and ``+key=value`` adds,
- mandatory ``???`` markers (an unselected mandatory group raises),
- user-extensible search path via the ``SHEEPRL_SEARCH_PATH`` env var
  (parity with the reference's hydra_plugins/sheeprl_search_path.py).

Composition output is a plain :class:`sheeprl_tpu.utils.utils.dotdict`.
"""

from __future__ import annotations

import copy
import datetime
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import yaml

from sheeprl_tpu.utils.utils import dotdict, get_by_path, set_by_path

MISSING = "???"

_PACKAGE_RE = re.compile(r"^#\s*@package\s+(\S+)\s*$", re.MULTILINE)
_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


class _YamlLoader(yaml.SafeLoader):
    """SafeLoader with YAML-1.2 float resolution: PyYAML's 1.1 regex parses
    `1e-4` (no dot) as a *string*, which silently poisons optimizer configs."""


_YamlLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
        |\.[0-9_]+(?:[eE][-+][0-9]+)?
        |[-+]?\.(?:inf|Inf|INF)
        |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def _yaml_load(text: str) -> Any:
    return yaml.load(text, Loader=_YamlLoader)


class ConfigError(Exception):
    pass


class MandatoryValueError(ConfigError):
    pass


def default_config_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "configs")


def search_paths() -> List[str]:
    """Config roots, highest priority first. Users prepend their own roots via
    SHEEPRL_SEARCH_PATH (a ``:``-separated list of directories)."""
    paths = []
    env = os.environ.get("SHEEPRL_SEARCH_PATH", "")
    for entry in env.split(":"):
        entry = entry.strip()
        if not entry:
            continue
        # Accept both plain paths and hydra-style "file://<path>" entries.
        if entry.startswith("file://"):
            entry = entry[len("file://") :]
        if os.path.isdir(entry):
            paths.append(entry)
    paths.append(default_config_dir())
    return paths


@dataclass
class _Entry:
    """One node of the expanded defaults tree."""

    group: str  # group path relative to config root, "" for same-dir include
    option: str
    package: str  # absolute package ("" == global)
    content: Dict[str, Any] = field(default_factory=dict)


def _strip_ext(name: str) -> str:
    return name[:-5] if isinstance(name, str) and name.endswith(".yaml") else name


def _join_pkg(parent: str, child: str) -> str:
    if child.startswith("_global_"):
        rest = child[len("_global_") :].lstrip(".")
        return rest
    if not parent:
        return child
    if not child:
        return parent
    return f"{parent}.{child}"


class Composer:
    def __init__(self, roots: Optional[Sequence[str]] = None):
        self.roots = list(roots) if roots else search_paths()
        # compose() walks the tree repeatedly (choices fixed point + expand);
        # files are immutable within one compose, so parse each path once.
        self._file_cache: Dict[str, Tuple[Dict[str, Any], List[Any], Optional[str]]] = {}

    # ------------------------------------------------------------------ files
    def _find_file(self, group: str, option: str) -> Optional[str]:
        option = _strip_ext(option)
        for root in self.roots:
            path = os.path.join(root, group, option + ".yaml") if group else os.path.join(root, option + ".yaml")
            if os.path.isfile(path):
                return path
        return None

    def is_group(self, name: str) -> bool:
        return any(os.path.isdir(os.path.join(root, name)) for root in self.roots)

    def _load_file(self, group: str, option: str) -> Tuple[Dict[str, Any], List[Any], Optional[str]]:
        """Returns (content-without-defaults, defaults list, package header)."""
        path = self._find_file(group, option)
        if path is None:
            raise ConfigError(f"Config file not found: group='{group}' option='{option}' (roots={self.roots})")
        cached = self._file_cache.get(path)
        if cached is not None:
            return cached
        with open(path) as fp:
            text = fp.read()
        pkg_match = _PACKAGE_RE.search(text)
        pkg_header = pkg_match.group(1) if pkg_match else None
        content = _yaml_load(text) or {}
        if not isinstance(content, dict):
            raise ConfigError(f"Config file {path} must contain a mapping at top level")
        defaults = content.pop("defaults", [])
        self._file_cache[path] = (content, defaults, pkg_header)
        return content, defaults, pkg_header

    # -------------------------------------------------------------- expansion
    @staticmethod
    def _parse_entry(raw: Any, group: str, own_pkg: str):
        """Parse one defaults-list dict entry into
        (is_override, full_group, choice_key, child_pkg, default_option).

        Single source of truth for both the choices walk and the expansion
        walk — the choice_key computed here must be identical in both, or
        `override` directives silently stop applying.
        """
        if not isinstance(raw, dict) or len(raw) != 1:
            raise ConfigError(f"Malformed defaults entry {raw!r} in group '{group}'")
        k, v = next(iter(raw.items()))
        k = k.strip()
        is_override = k.startswith("override ")
        if is_override:
            k = k[len("override ") :].strip()
        at_pkg = None
        if "@" in k:
            k, at_pkg = k.split("@", 1)
        absolute = k.startswith("/")
        g = k.lstrip("/")
        full_group = g if (absolute or not group) else f"{group}/{g}"
        if at_pkg is not None:
            child_pkg = _join_pkg(own_pkg, at_pkg)
            choice_key = f"{full_group}@{child_pkg}"
        else:
            child_pkg = _join_pkg(own_pkg, os.path.basename(full_group))
            choice_key = full_group
        return is_override, full_group, choice_key, child_pkg, _strip_ext(v) if isinstance(v, str) else v
    def _expand(
        self,
        group: str,
        option: str,
        parent_pkg: str,
        choices: Dict[str, str],
        out: List[_Entry],
        seen: Optional[set] = None,
    ) -> None:
        """DFS-expand a config file into an ordered list of merge entries."""
        seen = seen or set()
        key = (group, option)
        if key in seen:
            raise ConfigError(f"Cyclic defaults detected at {key}")
        seen = seen | {key}

        content, defaults, pkg_header = self._load_file(group, option)
        if pkg_header is not None:
            own_pkg = "" if pkg_header == "_global_" else _join_pkg("", pkg_header)
        else:
            own_pkg = parent_pkg

        entries: List[Any] = list(defaults)
        if not any(e == "_self_" for e in entries):
            entries.insert(0, "_self_")

        for raw in entries:
            if raw == "_self_":
                out.append(_Entry(group, option, own_pkg, content))
                continue
            if isinstance(raw, str):
                # Same-group include, e.g. "- dreamer_v3" inside algo/.
                self._expand(group, _strip_ext(raw), own_pkg, choices, out, seen)
                continue
            is_override, full_group, choice_key, child_pkg, default_opt = self._parse_entry(raw, group, own_pkg)
            if is_override:
                # Choice already recorded during the choices pass; skip here.
                continue
            sel = choices.get(choice_key, default_opt)
            if sel is None:
                continue
            sel = _strip_ext(sel)
            if sel == MISSING:
                raise MandatoryValueError(
                    f"You must specify '{full_group}', e.g. with the CLI override '{full_group}=<option>'"
                )
            self._expand(full_group, sel, child_pkg, choices, out, seen)

    def _collect_choices(
        self,
        group: str,
        option: str,
        parent_pkg: str,
        choices: Dict[str, str],
        cli_choices: Dict[str, str],
        seen: Optional[set] = None,
    ) -> None:
        """Walk the defaults tree recording `override` directives (walk order:
        later wins) so that a second expansion pass can use the final
        selections. Choice keys are ``group`` or ``group@absolute.package``.
        CLI choices always win."""
        seen = seen or set()
        key = (group, option)
        if key in seen:
            return
        seen = seen | {key}
        try:
            _, defaults, pkg_header = self._load_file(group, option)
        except ConfigError:
            return
        if pkg_header is not None:
            own_pkg = "" if pkg_header == "_global_" else _join_pkg("", pkg_header)
        else:
            own_pkg = parent_pkg
        for raw in defaults:
            if raw == "_self_" or isinstance(raw, str):
                if isinstance(raw, str) and raw != "_self_":
                    self._collect_choices(group, _strip_ext(raw), own_pkg, choices, cli_choices, seen)
                continue
            try:
                is_override, full_group, choice_key, child_pkg, default_opt = self._parse_entry(raw, group, own_pkg)
            except ConfigError:
                continue
            if is_override:
                if choice_key not in cli_choices:
                    choices[choice_key] = default_opt
                continue
            sel = cli_choices.get(choice_key, choices.get(choice_key, default_opt))
            if sel and sel != MISSING:
                self._collect_choices(full_group, sel, child_pkg, choices, cli_choices, seen)

    # ---------------------------------------------------------------- compose
    def compose(self, config_name: str = "config", overrides: Sequence[str] = ()) -> dotdict:
        cli_choices, dotted = self._parse_overrides(overrides)

        # Fixed-point choice collection: overrides discovered in newly selected
        # files may change selections which expose further overrides.
        choices: Dict[str, str] = {}
        for _ in range(8):
            before = dict(choices)
            self._collect_choices("", config_name, "", choices, cli_choices)
            if choices == before:
                break
        choices.update(cli_choices)

        out: List[_Entry] = []
        self._expand("", config_name, "", choices, out)

        result: Dict[str, Any] = {}
        for entry in out:
            node = copy.deepcopy(entry.content)
            if entry.package:
                wrapped: Dict[str, Any] = {}
                set_by_path(wrapped, entry.package, node)
                node = wrapped
            _deep_merge(result, node)

        _sentinel = object()
        # Applied in CLI order so '+a.b={}' can introduce a key that a later
        # plain 'a.b.c=1' override targets (Hydra applies in list order).
        for path, value, is_add in dotted:
            if not is_add and get_by_path(result, path, _sentinel) is _sentinel:
                # Hydra semantics: a plain override must target an existing
                # key; typos should fail loudly. New keys use '+key=value'.
                raise ConfigError(
                    f"Could not override '{path}': no such key in the composed config. "
                    f"Use '+{path}={value}' to add a new key."
                )
            set_by_path(result, path, value)

        result = _resolve_interpolations(result)
        return dotdict(result)

    def _parse_overrides(self, overrides: Sequence[str]):
        cli_choices: Dict[str, str] = {}
        dotted: List[Tuple[str, Any, bool]] = []  # (path, value, is_add), CLI order
        for ov in overrides:
            if "=" not in ov:
                raise ConfigError(f"Override '{ov}' must be of the form key=value")
            k, v = ov.split("=", 1)
            k = k.strip()
            if k.startswith("+"):
                dotted.append((k[1:], _parse_value(v), True))
                continue
            group_key = k.split("@", 1)[0]
            full_key = k.lstrip("/")  # keeps any @pkg suffix for scoped choices
            if "." not in group_key and (self.is_group(group_key) or self._find_file(group_key, _strip_ext(v)) is not None):
                cli_choices[full_key] = _strip_ext(v)
            elif "/" in group_key and self.is_group(group_key.lstrip("/").rsplit("/", 1)[0]):
                cli_choices[full_key] = _strip_ext(v)
            else:
                dotted.append((k, _parse_value(v), False))
        return cli_choices, dotted


def _parse_value(text: str) -> Any:
    try:
        return _yaml_load(text)
    except yaml.YAMLError:
        return text


def _deep_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> Dict[str, Any]:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
    return dst


def _resolve_interpolations(root: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve ${a.b.c} references and ${now:fmt} resolver calls."""

    resolving: set = set()

    def resolve_value(value: Any) -> Any:
        if isinstance(value, str):
            return resolve_str(value)
        if isinstance(value, dict):
            return {k: resolve_value(v) for k, v in value.items()}
        if isinstance(value, list):
            return [resolve_value(v) for v in value]
        return value

    def resolve_str(text: str) -> Any:
        m = _INTERP_RE.fullmatch(text)
        if m:
            return resolve_expr(m.group(1))
        # Embedded interpolation inside a larger string: substitute textually.
        def sub(match: "re.Match[str]") -> str:
            val = resolve_expr(match.group(1))
            return str(val)

        prev = None
        while prev != text and _INTERP_RE.search(text):
            prev = text
            text = _INTERP_RE.sub(sub, text)
        return text

    def resolve_expr(expr: str) -> Any:
        expr = expr.strip()
        if expr.startswith("now:"):
            return datetime.datetime.now().strftime(expr[len("now:") :])
        if expr.startswith("oc.env:"):
            parts = expr[len("oc.env:") :].split(",", 1)
            return os.environ.get(parts[0], parts[1] if len(parts) > 1 else None)
        if expr in resolving:
            raise ConfigError(f"Interpolation cycle detected at ${{{expr}}}")
        resolving.add(expr)
        try:
            target = get_by_path(root, expr, default=ConfigError)
            if target is ConfigError:
                raise ConfigError(f"Interpolation key not found: ${{{expr}}}")
            return resolve_value(copy.deepcopy(target))
        finally:
            resolving.discard(expr)

    return resolve_value(root)


def compose(config_name: str = "config", overrides: Sequence[str] = (), roots: Optional[Sequence[str]] = None) -> dotdict:
    """Compose the framework config. Main entry used by the CLI and tests."""
    return Composer(roots).compose(config_name, overrides)
