from sheeprl_tpu.models.models import (
    CNN,
    DeCNN,
    LayerNorm,
    LayerNormChannelLast,
    LayerNormGRUCell,
    MLP,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    get_activation,
)

__all__ = [
    "CNN",
    "DeCNN",
    "LayerNorm",
    "LayerNormChannelLast",
    "LayerNormGRUCell",
    "MLP",
    "MultiDecoder",
    "MultiEncoder",
    "NatureCNN",
    "get_activation",
]
