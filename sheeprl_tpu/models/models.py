"""NN building blocks (flax.linen), TPU-first.

Capability parity with the reference model library
(sheeprl/models/models.py:16-525 and sheeprl/utils/model.py:34-223), designed
for XLA:TPU rather than translated from torch:

- Convolutions use **NHWC** layout — the TPU-native format (the reference is
  NCHW; here pixels stay channel-last from env to loss, so XLA never inserts
  transposes in front of the MXU).
- Per-layer dropout/norm/activation configurability is kept (reference
  `miniblock`, sheeprl/utils/model.py:34-88; order: layer → dropout → norm →
  activation), but layers are declared inline in `nn.compact` — shape
  inference removes the reference's input-size bookkeeping and dummy-forward
  probing (e.g. NatureCNN's probe at sheeprl/models/models.py:312-314).
- All blocks take a `dtype` (compute) / `param_dtype` pair wired from the
  precision policy; LayerNorm always computes statistics in fp32 and returns
  the input dtype (parity with the dtype-preserving LayerNorm,
  sheeprl/models/models.py:521-525 — and the right call on TPU where bf16
  accumulation of variance is lossy).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import os

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any
ActivationLike = Union[None, str, Callable[[jax.Array], jax.Array]]


_ACTIVATIONS: Dict[str, Callable[[jax.Array], jax.Array]] = {
    "relu": nn.relu,
    "tanh": jnp.tanh,
    "silu": nn.silu,
    "swish": nn.silu,
    "gelu": nn.gelu,
    "elu": nn.elu,
    "leaky_relu": nn.leaky_relu,
    "sigmoid": nn.sigmoid,
    "identity": lambda x: x,
    "none": lambda x: x,
}


def get_activation(act: ActivationLike) -> Callable[[jax.Array], jax.Array]:
    """Resolve an activation given by name (config-friendly) or callable."""
    if act is None:
        return _ACTIVATIONS["identity"]
    if callable(act):
        return act
    try:
        return _ACTIVATIONS[str(act).lower()]
    except KeyError:
        raise ValueError(f"Unknown activation '{act}'. Valid: {sorted(_ACTIVATIONS)}") from None


def _per_layer(spec: Any, num_layers: int, what: str) -> Sequence[Any]:
    """Broadcast a single spec to `num_layers`, or validate a per-layer list
    (reference `create_layers`, sheeprl/utils/model.py:91-139)."""
    if isinstance(spec, (list, tuple)):
        if len(spec) != num_layers:
            raise ValueError(f"Got {len(spec)} {what} specs for {num_layers} layers")
        return list(spec)
    return [spec] * num_layers


class LayerNorm(nn.Module):
    """LayerNorm computing statistics in fp32, returning the input dtype.

    Parity: dtype-preserving LayerNorm (sheeprl/models/models.py:521-525).
    On TPU this keeps the reduction out of bf16 while leaving the surrounding
    matmuls in the compute dtype.
    """

    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        input_dtype = x.dtype
        out = nn.LayerNorm(
            epsilon=self.epsilon,
            use_scale=self.use_scale,
            use_bias=self.use_bias,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
        )(x.astype(jnp.float32))
        return out.astype(input_dtype)


# Channel-last is the native layout here, so the reference's
# LayerNormChannelLast (sheeprl/models/models.py:507-518) — a permute/LN/
# permute sandwich around NCHW — degenerates to plain LayerNorm over the
# trailing channel dim. Exported under the same name for config parity.
LayerNormChannelLast = LayerNorm


_NORMS: Dict[str, Callable[..., nn.Module]] = {
    "layer_norm": LayerNorm,
    "layer_norm_channel_last": LayerNormChannelLast,
    "rms_norm": nn.RMSNorm,
}


def make_norm(norm: Union[None, str, Callable[..., nn.Module]], args: Optional[dict]) -> Optional[nn.Module]:
    if norm is None:
        return None
    args = dict(args or {})
    # torch LayerNorm configs carry normalized_shape; flax infers it.
    args.pop("normalized_shape", None)
    # torch spells the epsilon kwarg "eps".
    if "eps" in args:
        args["epsilon"] = args.pop("eps")
    if callable(norm) and not isinstance(norm, str):
        return norm(**args)
    try:
        return _NORMS[str(norm).lower()](**args)
    except KeyError:
        raise ValueError(f"Unknown norm layer '{norm}'. Valid: {sorted(_NORMS)}") from None


def _apply_block(
    x: jax.Array,
    *,
    dropout: Optional[float],
    norm: Union[None, str, Callable[..., nn.Module]],
    norm_args: Optional[dict],
    activation: ActivationLike,
    deterministic: bool,
) -> jax.Array:
    """Post-layer stack in reference miniblock order: dropout → norm → act
    (sheeprl/utils/model.py:80-88)."""
    if dropout:
        x = nn.Dropout(rate=float(dropout), deterministic=deterministic)(x)
    norm_mod = make_norm(norm, norm_args)
    if norm_mod is not None:
        x = norm_mod(x)
    return get_activation(activation)(x)


class MLP(nn.Module):
    """Configurable MLP backbone (reference: sheeprl/models/models.py:16-119).

    `hidden_sizes` hidden blocks of Dense → [dropout] → [norm] → activation,
    plus an optional bare `output_dim` Dense head. `flatten_dim` flattens the
    input starting at that axis (negative axes supported), matching the
    reference's `obs.flatten(self._flatten_dim)`.

    Any of `activation`, `norm_layer`, `norm_args`, `dropout`, `layer_args`
    may be a per-layer list of length `len(hidden_sizes)`.
    """

    hidden_sizes: Sequence[int] = ()
    output_dim: Optional[int] = None
    activation: Union[ActivationLike, Sequence[ActivationLike]] = "relu"
    norm_layer: Any = None
    norm_args: Any = None
    dropout: Union[None, float, Sequence[Optional[float]]] = None
    layer_args: Any = None
    flatten_dim: Optional[int] = None
    kernel_init: Optional[Callable] = None
    output_kernel_init: Optional[Callable] = None
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        if len(self.hidden_sizes) < 1 and self.output_dim is None:
            raise ValueError("The number of layers should be at least 1.")
        if self.flatten_dim is not None:
            start = self.flatten_dim % x.ndim
            x = x.reshape(*x.shape[:start], -1)
        n = len(self.hidden_sizes)
        acts = _per_layer(self.activation, n, "activation")
        norms = _per_layer(self.norm_layer, n, "norm")
        norm_args = _per_layer(self.norm_args, n, "norm_args")
        drops = _per_layer(self.dropout, n, "dropout")
        largs = _per_layer(self.layer_args, n, "layer_args")
        x = x.astype(self.dtype)
        for i, size in enumerate(self.hidden_sizes):
            kw = dict(largs[i] or {})
            init_kw = {"kernel_init": self.kernel_init} if self.kernel_init is not None else {}
            x = nn.Dense(
                size,
                use_bias=kw.get("bias", True),
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=f"dense_{i}",
                **init_kw,
            )(x)
            x = _apply_block(
                x,
                dropout=drops[i],
                norm=norms[i],
                norm_args=norm_args[i],
                activation=acts[i],
                deterministic=deterministic,
            )
        if self.output_dim is not None:
            out_init = self.output_kernel_init or self.kernel_init
            init_kw = {"kernel_init": out_init} if out_init is not None else {}
            x = nn.Dense(
                self.output_dim, dtype=self.dtype, param_dtype=self.param_dtype, name="output", **init_kw
            )(x)
        return x


def _pair(v: Union[int, Sequence[int]]) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return tuple(v)  # type: ignore[return-value]


class CNN(nn.Module):
    """Configurable conv stack, NHWC (reference: sheeprl/models/models.py:122-202).

    `layer_args` per layer supports torch-style keys {kernel_size, stride,
    padding, bias} so algorithm configs stay portable; padding ints are
    symmetric pads (torch semantics), strings pass through to XLA ("SAME",
    "VALID").
    """

    hidden_channels: Sequence[int]
    activation: Union[ActivationLike, Sequence[ActivationLike]] = "relu"
    norm_layer: Any = None
    norm_args: Any = None
    dropout: Union[None, float, Sequence[Optional[float]]] = None
    layer_args: Any = None
    kernel_init: Optional[Callable] = None
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        n = len(self.hidden_channels)
        if n < 1:
            raise ValueError("The number of layers should be at least 1.")
        acts = _per_layer(self.activation, n, "activation")
        norms = _per_layer(self.norm_layer, n, "norm")
        norm_args = _per_layer(self.norm_args, n, "norm_args")
        drops = _per_layer(self.dropout, n, "dropout")
        largs = _per_layer(self.layer_args, n, "layer_args")
        init_kw = {"kernel_init": self.kernel_init} if self.kernel_init is not None else {}
        x = x.astype(self.dtype)
        for i, ch in enumerate(self.hidden_channels):
            kw = dict(largs[i] or {})
            kernel = _pair(kw.get("kernel_size", 3))
            stride = _pair(kw.get("stride", 1))
            pad = kw.get("padding", 0)
            padding = [(p, p) for p in _pair(pad)] if not isinstance(pad, str) else pad
            x = nn.Conv(
                ch,
                kernel_size=kernel,
                strides=stride,
                padding=padding,
                use_bias=kw.get("bias", True),
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=f"conv_{i}",
                **init_kw,
            )(x)
            x = _apply_block(
                x,
                dropout=drops[i],
                norm=norms[i],
                norm_args=norm_args[i],
                activation=acts[i],
                deterministic=deterministic,
            )
        return x


class DeCNN(nn.Module):
    """Configurable transposed-conv stack, NHWC (reference: models.py:205-285).

    torch ConvTranspose2d-style layer_args {kernel_size, stride, padding,
    output_padding, bias} are mapped onto lax.conv_transpose padding so a
    torch-shaped decoder config produces identical output spatial sizes:
    out = (in-1)*stride - 2*pad + kernel + output_padding.
    """

    hidden_channels: Sequence[int]
    activation: Union[ActivationLike, Sequence[ActivationLike]] = "relu"
    norm_layer: Any = None
    norm_args: Any = None
    dropout: Union[None, float, Sequence[Optional[float]]] = None
    layer_args: Any = None
    kernel_init: Any = None
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        n = len(self.hidden_channels)
        if n < 1:
            raise ValueError("The number of layers should be at least 1.")
        acts = _per_layer(self.activation, n, "activation")
        norms = _per_layer(self.norm_layer, n, "norm")
        norm_args = _per_layer(self.norm_args, n, "norm_args")
        drops = _per_layer(self.dropout, n, "dropout")
        largs = _per_layer(self.layer_args, n, "layer_args")
        kernel_inits = _per_layer(self.kernel_init, n, "kernel_init")
        x = x.astype(self.dtype)
        for i, ch in enumerate(self.hidden_channels):
            kw = dict(largs[i] or {})
            kernel = _pair(kw.get("kernel_size", 3))
            stride = _pair(kw.get("stride", 1))
            pad = _pair(kw.get("padding", 0))
            out_pad = _pair(kw.get("output_padding", 0))
            # torch transposed-conv output size, expressed as lax.conv_transpose
            # explicit padding: lax pads (k-1-p) on each side of the dilated
            # input; output_padding extends the high side.
            padding = [
                (kernel[0] - 1 - pad[0], kernel[0] - 1 - pad[0] + out_pad[0]),
                (kernel[1] - 1 - pad[1], kernel[1] - 1 - pad[1] + out_pad[1]),
            ]
            init_kw = {"kernel_init": kernel_inits[i]} if kernel_inits[i] is not None else {}
            x = nn.ConvTranspose(
                ch,
                kernel_size=kernel,
                strides=stride,
                padding=padding,
                use_bias=kw.get("bias", True),
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=f"deconv_{i}",
                **init_kw,
            )(x)
            x = _apply_block(
                x,
                dropout=drops[i],
                norm=norms[i],
                norm_args=norm_args[i],
                activation=acts[i],
                deterministic=deterministic,
            )
        return x


class NatureCNN(nn.Module):
    """DQN Nature trunk + dense head (reference: models.py:288-328).

    Conv 32/64/64 with (8,4)/(4,2)/(3,1) kernels/strides, flatten, Dense to
    `features_dim`, ReLU. Input NHWC. No dummy-forward probing needed: flax
    infers the flattened dim at init.
    """

    features_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = CNN(
            hidden_channels=(32, 64, 64),
            layer_args=[
                {"kernel_size": 8, "stride": 4},
                {"kernel_size": 4, "stride": 2},
                {"kernel_size": 3, "stride": 1},
            ],
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="cnn",
        )(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.Dense(self.features_dim, dtype=self.dtype, param_dtype=self.param_dtype, name="fc")(x)
        return nn.relu(x)


class LayerNormGRUCell(nn.Module):
    """Hafner GRU cell: LN after the fused input projection, `update-1` bias,
    tanh candidate gated by reset (reference: sheeprl/models/models.py:331-410,
    itself from danijar/dreamerv2 nets.py).

        x = LN(W [h, x])                (single fused matmul — MXU-friendly)
        reset, cand, update = split(x, 3)
        cand = tanh(sigmoid(reset) * cand)
        update = sigmoid(update - 1)
        h' = update * cand + (1 - update) * h

    This is the per-step body of every Dreamer RSSM; the sequence loop lives
    in the caller as `lax.scan` (never a Python loop — SURVEY §7.2). With
    ``fused=None`` (auto) the standard bias+LN configuration routes through
    the Pallas kernel (models/pallas_gru.py) on TPU — same math, same param
    tree, one VMEM-resident epilogue instead of an HBM round-trip of z.
    """

    hidden_size: int
    bias: bool = True
    layer_norm: bool = True
    fused: Optional[bool] = None  # None = auto (TPU + bias + LN)
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> jax.Array:
        inp = jnp.concatenate([h.astype(self.dtype), x.astype(self.dtype)], axis=-1)
        # Auto default is OFF: the measured A/B (PROFILE.md) shows the fused
        # kernel wins at the cell level forward (1.45x at B=1024) but is
        # neutral-to-slightly-negative inside the full DV3 train step, where
        # convs dominate and the custom-VJP boundary blocks XLA cross-fusion.
        # ONE knob: opt in per-module (fused=True) or globally via
        # SHEEPRL_TPU_FUSED_GRU=1 (read only here).
        use_fused = (
            self.fused
            if self.fused is not None
            else os.environ.get("SHEEPRL_TPU_FUSED_GRU", "0") == "1"
        )
        if use_fused and self.layer_norm:
            from sheeprl_tpu.models.pallas_gru import fused_ln_gru

            # Raw params declared under the SAME tree as the unfused path
            # (linear/{kernel[,bias]}, norm/LayerNorm_0/{scale,bias}) so
            # checkpoints and the `fused` flag are interchangeable. The
            # Dreamer RSSM config (bias=False: LN provides the shift) feeds
            # the kernel a constant zero bias.
            kernel, dense_bias = _DenseParams(
                3 * self.hidden_size, self.bias, self.param_dtype, name="linear"
            )(inp.shape[-1])
            if dense_bias is None:
                dense_bias = jnp.zeros((3 * self.hidden_size,), self.dtype)
            scale, ln_bias = _LayerNormParams(self.param_dtype, name="norm")(3 * self.hidden_size)
            return fused_ln_gru(
                inp,
                kernel.astype(self.dtype),
                dense_bias.astype(self.dtype),
                scale,
                ln_bias,
                h.astype(self.dtype),
            )
        z = nn.Dense(
            3 * self.hidden_size,
            use_bias=self.bias,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="linear",
        )(inp)
        if self.layer_norm:
            z = LayerNorm(param_dtype=self.param_dtype, name="norm")(z)
        reset, cand, update = jnp.split(z, 3, axis=-1)
        reset = nn.sigmoid(reset)
        cand = jnp.tanh(reset * cand)
        update = nn.sigmoid(update - 1)
        return update * cand + (1 - update) * h.astype(self.dtype)


class _DenseParams(nn.Module):
    """Param-holder mirroring nn.Dense's tree ({kernel, bias})."""

    features: int
    use_bias: bool
    param_dtype: Dtype

    @nn.compact
    def __call__(self, in_dim: int):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (in_dim, self.features), self.param_dtype
        )
        bias = (
            self.param("bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype)
            if self.use_bias
            else None
        )
        return kernel, bias


class _LayerNormInnerParams(nn.Module):
    param_dtype: Dtype

    @nn.compact
    def __call__(self, dim: int):
        scale = self.param("scale", nn.initializers.ones_init(), (dim,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(), (dim,), self.param_dtype)
        return scale, bias


class _LayerNormParams(nn.Module):
    """Param-holder mirroring models.LayerNorm's tree
    (norm/LayerNorm_0/{scale, bias})."""

    param_dtype: Dtype

    @nn.compact
    def __call__(self, dim: int):
        return _LayerNormInnerParams(self.param_dtype, name="LayerNorm_0")(dim)


class MultiEncoder(nn.Module):
    """Dict-obs fusion: concat CNN features with MLP features
    (reference: sheeprl/models/models.py:413-475).

    `cnn_encoder` / `mlp_encoder` are submodules taking the obs dict and
    returning a feature vector; at least one must be set.
    """

    cnn_encoder: Optional[nn.Module] = None
    mlp_encoder: Optional[nn.Module] = None

    def __post_init__(self) -> None:
        super().__post_init__()
        if self.cnn_encoder is None and self.mlp_encoder is None:
            raise ValueError("There must be at least one encoder, both cnn and mlp encoders are None")

    def __call__(self, obs: Dict[str, jax.Array], *args: Any, **kwargs: Any) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs, *args, **kwargs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs, *args, **kwargs))
        if len(outs) == 2:
            return jnp.concatenate(outs, axis=-1)
        return outs[0]


class MultiDecoder(nn.Module):
    """Latent → dict of per-key reconstructions
    (reference: sheeprl/models/models.py:478-504)."""

    cnn_decoder: Optional[nn.Module] = None
    mlp_decoder: Optional[nn.Module] = None

    def __post_init__(self) -> None:
        super().__post_init__()
        if self.cnn_decoder is None and self.mlp_decoder is None:
            raise ValueError("There must be a decoder, both cnn and mlp decoders are None")

    def __call__(self, x: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(x))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(x))
        return out
