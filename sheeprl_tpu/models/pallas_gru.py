"""Fused LayerNorm-GRU cell step as a Pallas TPU kernel.

The Hafner GRU cell (models.LayerNormGRUCell) is the per-step body of every
Dreamer RSSM scan — the hottest recurrent op in the framework. Unfused, XLA
materializes the projection z = [h, x] @ W (+b) to HBM, then reads it back
for LayerNorm and again for the gate math. This kernel keeps each [B_tile,
3H] row of z in VMEM: the matmul accumulates over D tiles on the MXU and the
epilogue (LayerNorm over the full 3H row + sigmoid/tanh gates + the convex
h-update) runs on the VPU before anything returns to HBM.

Gradients: `fused_ln_gru` carries a custom VJP. The forward kernel ALSO
emits the biased pre-LN projection z as a residual, so the backward never
recomputes the forward matmul — it differentiates the cheap elementwise
z -> out tail with plain jax and forms the three matmul gradients
(dz @ W^T, inp^T @ dz, sum dz) directly. Same FLOPs as XLA's unfused
backward, minus the fused forward's saved HBM traffic.

Dispatch: the kernel runs on TPU when the shapes satisfy the tiling
constraints (H multiple of 128, modest VMEM footprint); anything else —
CPU tests, tiny dry-run models, XL configs whose W tiles exceed VMEM —
falls back to the identical plain-jax computation. Whether the cell routes
here at all is decided by ONE knob in models.LayerNormGRUCell: the `fused`
flag, whose auto default reads SHEEPRL_TPU_FUSED_GRU (default off).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LN_EPS = 1e-5  # models.LayerNorm default
_B_TILE = 256
_D_TILE = 512
# Per-grid-step VMEM budget for the W tile (f32): D_TILE * 3H * 4 bytes
_W_TILE_BUDGET = 8 * 1024 * 1024


def _gates_from_z(z, scale, ln_bias, h):
    """The elementwise tail: biased pre-LN z [B, 3H] -> new state [B, H].
    Differentiated in the custom backward; must match the kernel epilogue."""
    zf = z.astype(jnp.float32)
    mu = zf.mean(-1, keepdims=True)
    var = ((zf - mu) ** 2).mean(-1, keepdims=True)
    zf = (zf - mu) * jax.lax.rsqrt(var + _LN_EPS)
    zf = zf * scale + ln_bias
    hidden = h.shape[-1]
    reset = jax.nn.sigmoid(zf[..., :hidden])
    cand = jnp.tanh(reset * zf[..., hidden : 2 * hidden])
    update = jax.nn.sigmoid(zf[..., 2 * hidden :] - 1)
    hf = h.astype(jnp.float32)
    return (update * cand + (1 - update) * hf).astype(h.dtype)


def _plain_ln_gru(inp, w, b, scale, ln_bias, h):
    """Reference computation (identical math to models.LayerNormGRUCell)."""
    z = (inp @ w + b).astype(jnp.float32)
    return _gates_from_z(z, scale, ln_bias, h), z


def _kernel(inp_ref, w_ref, b_ref, scale_ref, lnb_ref, h_ref, out_ref, z_ref, acc_ref, *, hidden: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        inp_ref[:].astype(jnp.float32),
        w_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == pl.num_programs(1) - 1)
    def _epilogue():
        z = acc_ref[:] + b_ref[:].astype(jnp.float32)
        z_ref[:] = z
        mu = z.mean(-1, keepdims=True)
        var = ((z - mu) ** 2).mean(-1, keepdims=True)
        z = (z - mu) * jax.lax.rsqrt(var + _LN_EPS)
        z = z * scale_ref[:].astype(jnp.float32) + lnb_ref[:].astype(jnp.float32)
        reset = jax.nn.sigmoid(z[:, :hidden])
        cand = jnp.tanh(reset * z[:, hidden : 2 * hidden])
        update = jax.nn.sigmoid(z[:, 2 * hidden :] - 1)
        h = h_ref[:].astype(jnp.float32)
        out_ref[:] = (update * cand + (1 - update) * h).astype(out_ref.dtype)


def _pallas_ln_gru(inp, w, b, scale, ln_bias, h, *, interpret: bool = False):
    """Returns (new_state [B, H], biased pre-LN z [B, 3H] f32)."""
    batch, d = inp.shape
    hidden = h.shape[-1]
    h3 = 3 * hidden

    # Pad batch to the f32 sublane tile and D to the lane tile; zero rows and
    # zero K-columns do not perturb the matmul.
    pb = (-batch) % 8
    pd = (-d) % 128
    if pb:
        inp = jnp.pad(inp, ((0, pb), (0, 0)))
        h = jnp.pad(h, ((0, pb), (0, 0)))
    if pd:
        inp = jnp.pad(inp, ((0, 0), (0, pd)))
        w = jnp.pad(w, ((0, pd), (0, 0)))
    bp, dp = inp.shape

    b_tile = min(_B_TILE, bp)
    # Adapt the D tile to the VMEM budget: wide hidden states (L/XL configs,
    # 3H up to 12k) shrink the K-tile instead of losing the kernel.
    d_tile = min(_D_TILE, dp)
    while d_tile > 128 and d_tile * h3 * 4 > _W_TILE_BUDGET:
        d_tile //= 2
    grid = (pl.cdiv(bp, b_tile), pl.cdiv(dp, d_tile))

    out, z = pl.pallas_call(
        functools.partial(_kernel, hidden=hidden),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_tile, d_tile), lambda i, k: (i, k)),
            pl.BlockSpec((d_tile, h3), lambda i, k: (k, 0)),
            pl.BlockSpec((1, h3), lambda i, k: (0, 0)),
            pl.BlockSpec((1, h3), lambda i, k: (0, 0)),
            pl.BlockSpec((1, h3), lambda i, k: (0, 0)),
            pl.BlockSpec((b_tile, hidden), lambda i, k: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b_tile, hidden), lambda i, k: (i, 0)),
            pl.BlockSpec((b_tile, h3), lambda i, k: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, hidden), h.dtype),
            jax.ShapeDtypeStruct((bp, h3), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b_tile, h3), jnp.float32)],
        interpret=interpret,
    )(inp, w, b.reshape(1, -1), scale.reshape(1, -1), ln_bias.reshape(1, -1), h)
    return out[:batch], z[:batch]


def _eligible(inp, w, h) -> bool:
    hidden = h.shape[-1]
    if hidden % 128 != 0:
        return False
    # The adaptive D-tiling floors at 128 lanes; beyond that the W tile
    # cannot fit the budget.
    if 128 * 3 * hidden * 4 > _W_TILE_BUDGET:
        return False
    return jax.default_backend() == "tpu"


@jax.custom_vjp
def fused_ln_gru(inp, w, b, scale, ln_bias, h):
    """One LN-GRU cell step: inp [B, D] (already concat of [h, x]), dense
    kernel w [D, 3H] + bias b [3H], LayerNorm scale/bias [3H], state h [B, H]
    -> new state [B, H]."""
    if _eligible(inp, w, h):
        return _pallas_ln_gru(inp, w, b, scale, ln_bias, h)[0]
    return _plain_ln_gru(inp, w, b, scale, ln_bias, h)[0]


def _fwd(inp, w, b, scale, ln_bias, h):
    if _eligible(inp, w, h):
        out, z = _pallas_ln_gru(inp, w, b, scale, ln_bias, h)
    else:
        out, z = _plain_ln_gru(inp, w, b, scale, ln_bias, h)
    return out, (inp, w, b, scale, ln_bias, h, z)


def _bwd(residuals, g):
    inp, w, b, scale, ln_bias, h, z = residuals
    # Elementwise tail gradient from the saved projection — no matmul
    # recompute.
    _, tail_vjp = jax.vjp(_gates_from_z, z, scale, ln_bias, h)
    dz, dscale, dln_bias, dh_tail = tail_vjp(g)
    dz = dz.astype(jnp.float32)
    dinp = (dz @ w.astype(jnp.float32).T).astype(inp.dtype)
    dw = (inp.astype(jnp.float32).T @ dz).astype(w.dtype)
    db = dz.sum(0).astype(b.dtype)
    return dinp, dw, db, dscale, dln_bias, dh_tail


fused_ln_gru.defvjp(_fwd, _bwd)
