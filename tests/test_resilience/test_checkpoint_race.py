"""Concurrent checkpoint traffic: two rank writers with rank-aware GC racing
a reader running the auto-resume discovery path, all in real spawn processes.

The invariant (same one test_checkpoint_atomic.py pins single-process): the
reader never observes a half-deleted checkpoint — every path the discovery
returns either digest-validates in full or has vanished atomically."""

import multiprocessing as mp
import os

import pytest

from sheeprl_tpu.utils.checkpoint import find_latest_valid_checkpoint, validate_checkpoint

pytestmark = pytest.mark.chaos


def test_writers_and_gc_never_expose_torn_latest_to_reader(tmp_path):
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    ctx = mp.get_context("spawn")
    stop_evt = ctx.Event()
    failures = ctx.Queue()

    import ckpt_race_workers

    reader = ctx.Process(
        target=ckpt_race_workers.reader, args=(str(ckpt_dir), stop_evt, failures), daemon=True
    )
    writers = [
        ctx.Process(
            target=ckpt_race_workers.writer, args=(str(ckpt_dir), rank, 8, 2), daemon=True
        )
        for rank in (0, 1)
    ]
    reader.start()
    for w in writers:
        w.start()
    for w in writers:
        w.join(timeout=120)
        assert w.exitcode == 0
    stop_evt.set()
    reader.join(timeout=30)

    assert failures.empty(), f"reader observed a torn checkpoint: {failures.get()}"
    # Reader exiting nonzero would mean it crashed rather than failed clean.
    assert reader.exitcode == 0

    # Quiesced end state: rank-aware GC kept exactly keep_last per rank, the
    # survivors are the newest steps, and everything left fully validates.
    names = sorted(os.listdir(ckpt_dir))
    assert not [n for n in names if n.startswith(".tmp-") or n.startswith(".trash-")]
    by_rank = {0: [], 1: []}
    for n in names:
        step, rank = n[len("ckpt_"):-len(".ckpt")].split("_")
        by_rank[int(rank)].append(int(step))
    for rank, steps in by_rank.items():
        assert sorted(steps) == [7, 8], f"rank {rank} kept {steps}"
    for n in names:
        assert validate_checkpoint(str(ckpt_dir / n), verify_digest=True)
    latest = find_latest_valid_checkpoint(str(ckpt_dir))
    assert latest is not None and os.path.basename(latest).startswith("ckpt_8_")
