"""Topology-elastic sharded checkpoints: a save on an 8-device mesh records
per-leaf shardings in the manifest, and the restore replays them against
whatever mesh exists at resume time — 8, 4, or 1 devices — with bit-exact
values (the payload is always full host arrays; only the layout adapts)."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from sheeprl_tpu.core import mesh as mesh_lib
from sheeprl_tpu.utils.checkpoint import (
    load_checkpoint,
    load_recorded_shardings,
    place_with_recorded_shardings,
    read_manifest,
    save_checkpoint,
    validate_checkpoint,
)


def _mesh(n, model_axis=1):
    return mesh_lib.build_mesh(jax.devices()[:n], model_axis_size=model_axis)


def _sharded_state(mesh):
    """Three layouts worth recording: data-sharded, model-sharded (TP), and
    replicated — plus a non-array aux leaf."""
    w_data = mesh_lib.put_sharded(
        np.arange(16 * 4, dtype=np.float32).reshape(16, 4),
        NamedSharding(mesh, PartitionSpec("data")),
    )
    w_model = mesh_lib.put_sharded(
        np.arange(8 * 8, dtype=np.float32).reshape(8, 8) * 0.5,
        NamedSharding(mesh, PartitionSpec(None, "model")),
    )
    bias = mesh_lib.put_sharded(
        np.linspace(-1.0, 1.0, 8).astype(np.float32),
        NamedSharding(mesh, PartitionSpec()),
    )
    return {"agent": {"w_data": w_data, "w_model": w_model, "bias": bias}, "iter_num": 3}


def _leaf_axes(arr):
    return tuple(arr.sharding.spec)


def test_manifest_records_per_leaf_shardings(tmp_path):
    mesh = _mesh(8, model_axis=2)
    path = str(tmp_path / "ckpt_8_0.ckpt")
    with mesh:
        save_checkpoint(path, _sharded_state(mesh))
    manifest = read_manifest(path)
    assert validate_checkpoint(path, verify_digest=True)
    recorded = manifest["shardings"]
    assert recorded["agent/w_data"]["spec"] == ["data"]
    assert recorded["agent/w_model"]["spec"] == [None, "model"]
    assert recorded["agent/bias"]["spec"] == []
    assert recorded["agent/w_data"]["mesh"] == {"data": 4, "model": 2}
    # The sidecar is backward compatible: same schema, just one more key.
    assert load_recorded_shardings(path) == recorded


@pytest.mark.parametrize("resume_devices,resume_model", [(8, 2), (4, 2), (1, 1)])
def test_restore_is_bit_exact_across_topologies(tmp_path, resume_devices, resume_model):
    save_mesh = _mesh(8, model_axis=2)
    path = str(tmp_path / "ckpt_8_0.ckpt")
    with save_mesh:
        state = _sharded_state(save_mesh)
        expected = {k: np.asarray(v) for k, v in state["agent"].items()}
        save_checkpoint(path, state)

    resume_mesh = _mesh(resume_devices, model_axis=resume_model)
    loaded = load_checkpoint(path)
    placed = place_with_recorded_shardings(
        loaded["agent"], load_recorded_shardings(path), resume_mesh, prefix="agent"
    )
    for key, exp in expected.items():
        np.testing.assert_array_equal(np.asarray(placed[key]), exp)

    # Layout adapted, not just replicated: the recorded spec survives on any
    # mesh that still has the axes (on the 1x1 mesh the axes have size 1, so
    # the same spec is trivially fully replicated).
    assert _leaf_axes(placed["w_data"]) == ("data",)
    assert _leaf_axes(placed["w_model"]) == (None, "model")
    if resume_devices == 1:
        assert placed["w_data"].sharding.is_fully_replicated
        assert placed["w_model"].sharding.is_fully_replicated


def test_non_divisible_dim_degrades_to_replicated(tmp_path):
    # Saved data-sharded over 8 rows on an 8x1 mesh; resumed on a 3-device
    # mesh whose data axis (3) does not divide 8 -> that dim replicates.
    save_mesh = _mesh(8)
    path = str(tmp_path / "ckpt_1_0.ckpt")
    with save_mesh:
        w = mesh_lib.put_sharded(
            np.arange(8 * 2, dtype=np.float32).reshape(8, 2),
            NamedSharding(save_mesh, PartitionSpec("data")),
        )
        save_checkpoint(path, {"agent": {"w": w}})
    resume_mesh = _mesh(3)
    placed = place_with_recorded_shardings(
        load_checkpoint(path)["agent"], load_recorded_shardings(path), resume_mesh, prefix="agent"
    )
    assert _leaf_axes(placed["w"]) == ()
    np.testing.assert_array_equal(
        np.asarray(placed["w"]), np.arange(16, dtype=np.float32).reshape(8, 2)
    )


def test_pre_elastic_checkpoint_falls_back_to_caller_rule(tmp_path):
    # Host-only state: nothing device-backed to record, so the manifest has
    # no shardings key and resumes go through the caller's static rule.
    path = str(tmp_path / "ckpt_2_0.ckpt")
    save_checkpoint(path, {"agent": {"w": np.ones((4, 4), np.float32)}})
    assert load_recorded_shardings(path) is None
    mesh = _mesh(4)
    sentinel = []

    def default(leaf):
        sentinel.append(True)
        return mesh_lib.put_sharded(np.asarray(leaf), NamedSharding(mesh, PartitionSpec()))

    placed = place_with_recorded_shardings(
        load_checkpoint(path)["agent"], {}, mesh, prefix="agent", default=default
    )
    assert sentinel  # unrecorded leaves routed through the fallback
    np.testing.assert_array_equal(np.asarray(placed["w"]), np.ones((4, 4), np.float32))
