"""EnvSupervisor: restart-with-backoff on worker death, deterministic
reseeding, and the max-restarts circuit breaker (dead-slice masking)."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.core.resilience import EnvSupervisor

OBS = gym.spaces.Box(-1.0, 1.0, (3,), np.float32)
ACT = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)


class FakeVec:
    """Minimal vector-env surface EnvSliceGroup relies on."""

    def __init__(self, n=2, fail_at=None):
        self.num_envs = n
        self.single_observation_space = OBS
        self.single_action_space = ACT
        self.metadata = {}
        self._fail_at = fail_at
        self._steps = 0
        self.reset_seed = None
        self.closed = False

    def reset(self, *, seed=None, options=None):
        self.reset_seed = seed
        return np.zeros((self.num_envs, 3), np.float32), {}

    def step(self, actions):
        self._steps += 1
        if self._fail_at is not None and self._steps >= self._fail_at:
            raise RuntimeError("simulated worker death")
        n = self.num_envs
        obs = np.full((n, 3), float(self._steps), np.float32)
        return obs, np.ones(n), np.zeros(n, bool), np.zeros(n, bool), {}

    def close(self, **kwargs):
        self.closed = True


def _broken_factory():
    raise RuntimeError("rebuild keeps failing")


def _supervisor(envs, factories, **kw):
    kw.setdefault("seed", 7)
    kw.setdefault("backoff_base_s", 1e-4)
    kw.setdefault("backoff_max_s", 1e-3)
    return EnvSupervisor(envs, factories, **kw)


def test_restart_reports_truncated_episode_boundary():
    crashy = FakeVec(fail_at=2)
    sup = _supervisor([FakeVec(), crashy], [FakeVec, FakeVec])
    out = sup.step_slice(1, None)  # step 1: healthy
    assert not out[3].any()
    with pytest.warns(UserWarning, match="restart 1/"):
        obs, rew, term, trunc, info = sup.step_slice(1, None)  # step 2: dies
    assert crashy.closed
    assert sup.restart_counts == [0, 1]
    # The crash surfaces as an episode boundary: zero reward, truncated=True,
    # never terminated — sequence samplers must not stitch across it.
    assert trunc.all() and not term.any()
    assert (rew == 0).all()
    assert info["env_restarted"].all() and info["_env_restarted"].all()
    # The replacement slice is live again.
    out = sup.step_slice(1, None)
    assert not out[3].any()


def test_restart_reseed_is_deterministic():
    crashy = FakeVec(fail_at=1)
    sup = _supervisor([crashy], [FakeVec])
    with pytest.warns(UserWarning):
        sup.step_slice(0, None)
    assert sup.envs[0].reset_seed == sup.restart_seed(0, 1)
    # Same run seed -> same restart seed stream; different seed -> different.
    twin = _supervisor([FakeVec()], [FakeVec])
    assert twin.restart_seed(0, 1) == sup.restart_seed(0, 1)
    other = _supervisor([FakeVec()], [FakeVec], seed=8)
    assert other.restart_seed(0, 1) != sup.restart_seed(0, 1)


def test_circuit_breaker_masks_dead_slice():
    sup = _supervisor(
        [FakeVec(), FakeVec(fail_at=1)], [FakeVec, _broken_factory], max_restarts=2
    )
    with pytest.warns(UserWarning, match="masking it out"):
        obs, rew, term, trunc, info = sup.step_slice(1, None)
    assert sup.dead_slices == [1]
    assert sup.restart_counts[1] == 2
    assert (obs == 0).all() and trunc.all() and (rew == 0).all()
    assert info["env_masked"].all()
    # Dead slices stay masked without new warnings; healthy ones keep going.
    out = sup.step_slice(1, None)
    assert out[3].all() and (out[0] == 0).all()
    healthy = sup.step_slice(0, None)
    assert not healthy[3].any()


def test_single_slice_exhaustion_raises():
    sup = _supervisor([FakeVec(fail_at=1)], [_broken_factory], max_restarts=1)
    with pytest.warns(UserWarning):
        with pytest.raises(RuntimeError, match="only slice"):
            sup.step_slice(0, None)


def test_reset_concatenates_slices_and_offsets_seeds():
    sup = _supervisor([FakeVec(), FakeVec()], [FakeVec, FakeVec])
    obs, _ = sup.reset(seed=3)
    assert obs.shape == (4, 3)
    assert sup.envs[0].reset_seed == 3
    assert sup.envs[1].reset_seed == 5
