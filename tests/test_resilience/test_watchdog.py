"""DispatchWatchdog: trips once per armed window on a hung dispatch, stays
silent on the fast path, and validates its trip-policy knob."""

import time
from contextlib import nullcontext

import pytest

from sheeprl_tpu.core.resilience import DispatchWatchdog, watch


def test_trips_once_on_hang(capsys):
    dog = DispatchWatchdog(timeout_s=0.05, on_trip="warn")
    try:
        with dog.guard("train_dispatch"):
            time.sleep(0.4)
    finally:
        dog.close()
    # One trip per armed window, however long the hang outlives the deadline.
    assert dog.trips == 1
    err = capsys.readouterr().err
    assert "train_dispatch" in err and "exceeded" in err


def test_fast_path_never_trips():
    dog = DispatchWatchdog(timeout_s=5.0)
    try:
        for _ in range(3):
            with dog.guard("quick"):
                pass
        time.sleep(0.05)
    finally:
        dog.close()
    assert dog.trips == 0


def test_disabled_watchdog_is_inert():
    assert isinstance(watch(None, "x"), type(nullcontext()))
    dog = DispatchWatchdog(timeout_s=0.0)
    with dog.guard("never-armed"):
        pass
    assert dog._thread is None  # monitor thread never started
    dog.close()
    assert dog.trips == 0


def test_invalid_on_trip_rejected():
    with pytest.raises(ValueError, match="warn|preempt|abort"):
        DispatchWatchdog(on_trip="explode")
