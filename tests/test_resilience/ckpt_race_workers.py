"""Spawn-process workers for the checkpoint writer/GC-vs-reader race test.

Module-level functions so the spawn start method can import them in the
child; each child re-imports jax on CPU (the parent's conftest env vars are
inherited through os.environ)."""

import os
import time


def writer(ckpt_dir, rank, steps, keep_last):
    """Save `steps` checkpoints for one rank, GCing down to keep_last after
    each — the concurrent-rank writer half of the race."""
    import numpy as np

    from sheeprl_tpu.utils.checkpoint import save_checkpoint

    for step in range(1, steps + 1):
        state = {
            "agent": {"w": np.full((16, 16), step * 10 + rank, np.float32)},
            "iter_num": step,
        }
        save_checkpoint(
            os.path.join(ckpt_dir, f"ckpt_{step}_{rank}.ckpt"), state, keep_last=keep_last
        )


def reader(ckpt_dir, stop_evt, failures):
    """Hammer the resume discovery path while writers save and GC.

    The torn-latest contract: any path the discovery returns either fully
    digest-validates, or has atomically vanished (GC renamed it away whole).
    A path that still exists on disk but fails digest validation is exactly
    the half-deleted window the rename-first GC must close."""
    from sheeprl_tpu.core.resilience import resolve_auto_resume
    from sheeprl_tpu.utils.checkpoint import (
        find_latest_valid_checkpoint,
        validate_checkpoint,
    )

    while not stop_evt.is_set():
        for path in (
            find_latest_valid_checkpoint(ckpt_dir),
            resolve_auto_resume("auto", search_root=os.path.dirname(ckpt_dir)),
        ):
            if path is None:
                continue
            if not validate_checkpoint(path, verify_digest=True) and os.path.isdir(path):
                failures.put(("torn", path, sorted(os.listdir(path))))
                return
        time.sleep(0.001)
