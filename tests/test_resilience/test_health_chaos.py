"""Health-sentinel chaos scenario driven through the CLI: a NaN reward is
injected into the env stream, the in-jit probes surface the blow-up at the
next metric interval, the preempt sentinel SIGTERMs the run, the guard
drains — and the tainted run's checkpoint save is VETOED, so the newest
on-disk checkpoint is from before the NaN and ``checkpoint.resume_from=auto``
restarts from healthy state and finishes."""

import json
import os

import jax
import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core import chaos
from sheeprl_tpu.utils.checkpoint import load_checkpoint, parse_ckpt_name

pytestmark = pytest.mark.chaos

TOTAL_STEPS = 128
INJECT_ENV_STEP = 9  # env 0's 9th step() -> policy step ~18, after the save at 16


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    chaos.reset()
    yield
    chaos.reset()


def _find_ckpts(root):
    found = []
    for r, dirs, _ in os.walk(root):
        for d in dirs:
            if d.startswith("ckpt_") and d.endswith(".ckpt"):
                found.append(os.path.realpath(os.path.join(r, d)))
    return sorted(found, key=lambda p: parse_ckpt_name(p)[0])


def _find_jsonls(root):
    return sorted(
        os.path.join(r, f)
        for r, _, files in os.walk(root)
        for f in files
        if f == "telemetry.jsonl"
    )


def _health_events(root):
    events = []
    for path in _find_jsonls(root):
        with open(path) as fp:
            for line in fp:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("type") == "health_event":
                    events.append(rec)
    return events


def _assert_finite(tree, *, skip=("rb",)):
    if isinstance(tree, dict):
        tree = {k: v for k, v in tree.items() if k not in skip}
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            assert np.isfinite(arr).all(), "checkpoint contains non-finite values"


def sac_args(total_steps=TOTAL_STEPS, **extra):
    args = [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.wrapper.id=continuous_dummy",
        "metric.log_level=1",
        "metric.log_every=4",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.per_rank_batch_size=4",
        "algo.learning_starts=4",
        "algo.hidden_size=8",
        "algo.run_test=False",
        f"algo.total_steps={total_steps}",
        "buffer.memmap=False",
        "buffer.size=64",
        "buffer.checkpoint=True",
        "checkpoint.every=8",
        "checkpoint.save_last=True",
        "fabric.accelerator=cpu",
        "health=on",
        "telemetry.enabled=True",
    ]
    for k, v in extra.items():
        args.append(f"{k}={v}")
    return args


def test_nan_reward_preempts_vetoes_save_and_auto_resumes(tmp_path):
    # Leg 1: NaN reward injected into env 0 mid-run. The poisoned batch NaNs
    # the losses/grads, the probe scalars carry that to the next interval
    # fetch, and the preempt sentinel SIGTERMs the run. run() returns
    # normally: the PreemptionGuard drains at the iteration boundary.
    run(
        sac_args(
            **{
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": (
                    f"[{{kind: nan_reward, env_rank: 0, at_step: {INJECT_ENV_STEP}}}]"
                ),
            }
        )
    )

    # The sentinel fired and was recorded as a structured event.
    events = _health_events(tmp_path)
    assert events, "no health_event records in telemetry.jsonl"
    assert any(e["kind"] == "nonfinite" for e in events)
    assert all(e["policy"] == "preempt" for e in events if e["kind"] == "nonfinite")

    # The run was cut short, and the taint veto held: every checkpoint on
    # disk is pre-blow-up — the newest one validates and holds only finite
    # parameters. (The drain save after the trip was skipped, which also
    # means no autoresume pointer: resume_from=auto falls back to the newest
    # valid checkpoint.)
    ckpts = _find_ckpts(tmp_path)
    assert ckpts, "no checkpoint survived the NaN run"
    last_good_step = parse_ckpt_name(ckpts[-1])[0]
    assert last_good_step < TOTAL_STEPS
    state = load_checkpoint(ckpts[-1])
    _assert_finite(state)

    # Leg 2: auto-resume restarts from the pre-NaN checkpoint and, with the
    # injector gone, trains through to completion.
    chaos.reset()
    run(sac_args(**{"checkpoint.resume_from": "auto:logs/runs"}))
    resumed = _find_ckpts(tmp_path)[-1]
    assert parse_ckpt_name(resumed)[0] == TOTAL_STEPS
    _assert_finite(load_checkpoint(resumed))
