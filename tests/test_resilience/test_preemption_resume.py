"""Chaos-marked end-to-end fault scenarios driven through the CLI:
SIGTERM mid-train -> drain -> preemption save -> auto-resume, and a
fail-point crash inside the checkpoint commit window.

All scenarios run deterministically on the virtual CPU platform; the
resumed run must land exactly where an uninterrupted run does."""

import json
import os

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core import chaos
from sheeprl_tpu.core.chaos import ChaosFault
from sheeprl_tpu.core.resilience import AUTORESUME_NAME
from sheeprl_tpu.utils.checkpoint import (
    find_latest_valid_checkpoint,
    load_checkpoint,
    parse_ckpt_name,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    # Keep logs/ out of the repo and injector state out of the next test.
    monkeypatch.chdir(tmp_path)
    chaos.reset()
    yield
    chaos.reset()


def _find_ckpts(root):
    found = []
    for r, dirs, _ in os.walk(root):
        for d in dirs:
            if d.startswith("ckpt_") and d.endswith(".ckpt"):
                found.append(os.path.realpath(os.path.join(r, d)))
    return sorted(found, key=lambda p: parse_ckpt_name(p)[0])


def _find_pointers(root):
    return [
        os.path.join(r, f)
        for r, _, files in os.walk(root)
        for f in files
        if f == AUTORESUME_NAME
    ]


def sac_args(total_steps=32, **extra):
    args = [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.wrapper.id=continuous_dummy",
        "metric.log_level=0",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.per_rank_batch_size=4",
        "algo.learning_starts=4",
        "algo.hidden_size=8",
        "algo.run_test=False",
        f"algo.total_steps={total_steps}",
        "buffer.memmap=False",
        "buffer.size=64",
        "buffer.checkpoint=True",
        "checkpoint.every=0",
        "checkpoint.save_last=True",
        "fabric.accelerator=cpu",
    ]
    for k, v in extra.items():
        args.append(f"{k}={v}")
    return args


def dv3_args(total_steps=8, **extra):
    args = [
        "exp=dreamer_v3",
        "env=dummy",
        "metric.log_level=0",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "env.screen_size=64",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.per_rank_batch_size=2",
        "algo.per_rank_sequence_length=1",
        "algo.horizon=2",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.stochastic_size=4",
        "algo.world_model.discrete_size=4",
        "algo.learning_starts=0",
        "algo.run_test=False",
        f"algo.total_steps={total_steps}",
        "buffer.memmap=False",
        "buffer.checkpoint=True",
        "checkpoint.every=0",
        "checkpoint.save_last=True",
        "fabric.accelerator=cpu",
    ]
    for k, v in extra.items():
        args.append(f"{k}={v}")
    return args


def test_sac_sigterm_preempt_then_auto_resume_matches_baseline(tmp_path, monkeypatch):
    # Uninterrupted baseline run.
    base_dir = tmp_path / "baseline"
    base_dir.mkdir()
    monkeypatch.chdir(base_dir)
    run(sac_args())
    baseline = _find_ckpts(base_dir)[-1]
    assert parse_ckpt_name(baseline)[0] == 32

    # Same run with SIGTERM injected at policy step 16: the guard drains,
    # saves, writes the auto-resume pointer, and the loop exits cleanly.
    chaos_dir = tmp_path / "chaos"
    chaos_dir.mkdir()
    monkeypatch.chdir(chaos_dir)
    run(
        sac_args(
            **{
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": "[{kind: sigterm, at_step: 16}]",
            }
        )
    )
    preempt_ckpt = _find_ckpts(chaos_dir)[-1]
    assert parse_ckpt_name(preempt_ckpt)[0] == 16
    pointers = _find_pointers(chaos_dir)
    assert len(pointers) == 1
    with open(pointers[0]) as fp:
        pointer = json.load(fp)
    assert os.path.realpath(pointer["ckpt_path"]) == preempt_ckpt
    assert pointer["signal"] == 15
    assert pointer["policy_step"] == 16

    # Resume from the pointer (checkpoint.resume_from=auto:<root>) and finish.
    chaos.reset()
    run(
        sac_args(
            **{
                "checkpoint.resume_from": "auto:logs/runs",
                "algo.learning_starts": 0,
            }
        )
    )
    resumed = _find_ckpts(chaos_dir)[-1]
    assert parse_ckpt_name(resumed)[0] == 32

    # Preempt + resume lands exactly where the uninterrupted run did: same
    # iteration counter and the same replay-buffer write position.
    a = load_checkpoint(baseline)
    b = load_checkpoint(resumed)
    assert a["iter_num"] == b["iter_num"]
    assert a["rb"]._pos == b["rb"]._pos
    assert a["rb"].buffer_size == b["rb"].buffer_size


def test_dreamer_v3_sigterm_preempt_then_auto_resume(tmp_path):
    run(
        dv3_args(
            **{
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": "[{kind: sigterm, at_step: 4}]",
            }
        )
    )
    preempt_ckpt = _find_ckpts(tmp_path)[-1]
    assert parse_ckpt_name(preempt_ckpt)[0] == 4
    assert len(_find_pointers(tmp_path)) == 1

    chaos.reset()
    run(dv3_args(**{"checkpoint.resume_from": "auto:logs/runs"}))
    resumed = _find_ckpts(tmp_path)[-1]
    assert parse_ckpt_name(resumed)[0] == 8


def test_crash_inside_commit_leaves_previous_snapshot_valid(tmp_path):
    # Arm a fail point that detonates inside save_checkpoint's commit window
    # at policy step 16; the periodic save at step 8 has already landed.
    with pytest.raises(ChaosFault):
        run(
            sac_args(
                **{
                    "checkpoint.every": 8,
                    "resilience.chaos.enabled": True,
                    "resilience.chaos.injectors": (
                        "[{kind: fail_point, name: checkpoint.before_commit, at_step: 16}]"
                    ),
                }
            )
        )
    ckpts = _find_ckpts(tmp_path)
    assert ckpts and parse_ckpt_name(ckpts[-1])[0] == 8
    ckpt_dir = os.path.dirname(ckpts[-1])
    # The torn save left no trace: no staging dirs, and the resume path
    # lands on the previous valid snapshot.
    assert not [n for n in os.listdir(ckpt_dir) if n.startswith(".tmp-")]
    assert find_latest_valid_checkpoint(ckpt_dir) == ckpts[-1]
