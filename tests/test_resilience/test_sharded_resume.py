"""Cross-topology resume of the SHARDED learner (ISSUE 20 satellite).

A fused-lane SAC run on the 8-shard mesh checkpoints data-sharded params and
a sharded device ring; the save path pulls full host arrays and records the
per-leaf shardings in the manifest (utils/checkpoint.py). Resuming must work
on ANY topology: an 8-shard save restores on 1 device and vice versa, and
replaying the recorded shardings against the resume mesh is bit-exact — only
the layout adapts, never the values."""

import os

import jax
import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core import mesh as mesh_lib
from sheeprl_tpu.utils.checkpoint import (
    load_checkpoint,
    load_recorded_shardings,
    place_with_recorded_shardings,
)

NEEDS_8 = pytest.mark.skipif(jax.device_count() < 8, reason="needs the 8-device CPU platform")


@pytest.fixture(autouse=True)
def _chdir_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)


def find_checkpoints(root):
    ckpts = []
    for r, dirs, _files in os.walk(root):
        for d in dirs:
            if d.startswith("ckpt_") and d.endswith(".ckpt"):
                ckpts.append(os.path.join(r, d))
    return sorted(ckpts)


def sac_shard_overrides(devices, **extra):
    args = [
        "exp=sac_anakin",
        "metric.log_level=0",
        "env.num_envs=8",
        "env.sync_env=True",
        "algo.fused_superstep_steps=4",
        "algo.fused_train_steps=4",
        "algo.total_steps=96",
        "algo.learning_starts=32",
        "algo.per_rank_batch_size=8",
        "algo.hidden_size=8",
        "algo.run_test=False",
        "algo.fused_rollout=True",
        "buffer.size=256",
        "buffer.memmap=False",
        "checkpoint.every=0",
        "checkpoint.save_last=True",
        "fabric.accelerator=cpu",
        f"fabric.devices={devices}",
    ]
    for k, v in extra.items():
        args.append(f"{k}={v}")
    return args


def _resume_args(devices, ckpt, total_steps=128):
    args = sac_shard_overrides(devices, **{"algo.total_steps": total_steps})
    args.append(f"checkpoint.resume_from={ckpt}")
    return args


@NEEDS_8
class TestCrossTopologyShardedResume:
    def test_shard8_save_resumes_on_single_device(self, tmp_path):
        run(sac_shard_overrides(8))
        ckpts = find_checkpoints(tmp_path / "logs")
        assert ckpts, "8-shard run wrote no checkpoint"
        saved = load_checkpoint(ckpts[-1])
        run(_resume_args(1, ckpts[-1]))
        resumed_ckpts = [c for c in find_checkpoints(tmp_path / "logs") if c not in ckpts]
        assert resumed_ckpts, "1-device resume wrote no checkpoint"
        resumed = load_checkpoint(resumed_ckpts[-1])
        assert resumed["iter_num"] > saved["iter_num"]
        assert resumed["batch_size"] == saved["batch_size"]

    def test_single_device_save_resumes_on_shard8(self, tmp_path):
        run(sac_shard_overrides(1))
        ckpts = find_checkpoints(tmp_path / "logs")
        assert ckpts, "1-device run wrote no checkpoint"
        saved = load_checkpoint(ckpts[-1])
        run(_resume_args(8, ckpts[-1]))
        resumed_ckpts = [c for c in find_checkpoints(tmp_path / "logs") if c not in ckpts]
        assert resumed_ckpts, "8-shard resume wrote no checkpoint"
        resumed = load_checkpoint(resumed_ckpts[-1])
        assert resumed["iter_num"] > saved["iter_num"]
        assert resumed["batch_size"] == saved["batch_size"]

    def test_recorded_shardings_replay_bit_exact_on_any_topology(self, tmp_path):
        """The PR 19 elastic seam on the sharded learner's artifact: replaying
        the 8-shard manifest's recorded shardings against a 1-device (and an
        8-device) mesh reproduces the host values bit for bit."""
        run(sac_shard_overrides(8))
        ckpts = find_checkpoints(tmp_path / "logs")
        assert ckpts
        loaded = load_checkpoint(ckpts[-1])
        recorded = load_recorded_shardings(ckpts[-1])
        assert recorded, "sharded save recorded no shardings manifest"
        host_leaves = jax.tree_util.tree_leaves(loaded["agent"])
        for n in (1, 8):
            mesh = mesh_lib.build_mesh(jax.devices()[:n])
            placed = place_with_recorded_shardings(
                loaded["agent"], recorded, mesh, prefix="agent"
            )
            for host, dev in zip(host_leaves, jax.tree_util.tree_leaves(placed)):
                np.testing.assert_array_equal(np.asarray(host), np.asarray(dev))
