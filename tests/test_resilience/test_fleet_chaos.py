"""Fleet chaos e2e, driven through the CLI on the virtual 8-device CPU mesh:
SIGKILL-grade replica death mid-run, learner preemption with a whole-fleet
drain, and topology-elastic resume of the preemption checkpoint on a smaller
mesh. The counters must agree with no-fault baselines — a supervised restart
is a throughput dip, not a numerics event."""

import os

import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core import chaos
from sheeprl_tpu.telemetry.registry import default_registry
from sheeprl_tpu.utils.checkpoint import (
    load_checkpoint,
    load_recorded_shardings,
    parse_ckpt_name,
    read_manifest,
    validate_checkpoint,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    chaos.reset()
    yield
    chaos.reset()


def _find_ckpts(root):
    found = []
    for r, dirs, _ in os.walk(root):
        for d in dirs:
            if d.startswith("ckpt_") and d.endswith(".ckpt"):
                found.append(os.path.realpath(os.path.join(r, d)))
    return sorted(found, key=lambda p: parse_ckpt_name(p)[0])


def _restarts():
    return default_registry().counter("fleet/replica_restarts").value


def sac_fleet_args(total_steps=32, **extra):
    args = [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.wrapper.id=continuous_dummy",
        "metric.log_level=0",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.per_rank_batch_size=4",
        "algo.learning_starts=2",
        "algo.hidden_size=8",
        "algo.run_test=False",
        f"algo.total_steps={total_steps}",
        "buffer.memmap=False",
        "buffer.size=64",
        "buffer.checkpoint=True",
        "checkpoint.every=0",
        "checkpoint.save_last=True",
        "fabric.accelerator=cpu",
        "fabric.devices=2",
        "fleet.replicas=2",
        "fleet.quorum=1",
        "fleet.param_sync_every=4",
    ]
    for k, v in extra.items():
        args.append(f"{k}={v}")
    return args


def ppo_fleet_args(total_steps=64, **extra):
    args = [
        "exp=ppo_decoupled",
        "env=dummy",
        "metric.log_level=0",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        f"algo.total_steps={total_steps}",
        "buffer.memmap=False",
        "checkpoint.every=0",
        "checkpoint.save_last=True",
        "fabric.accelerator=cpu",
        "fabric.devices=2",
        "fleet.replicas=2",
        "fleet.quorum=1",
    ]
    for k, v in extra.items():
        args.append(f"{k}={v}")
    return args


# ------------------------------------------------------- SIGKILL a replica
def test_sac_fleet_kill9_replica_is_restarted_and_run_completes(tmp_path, monkeypatch):
    # No-fault baseline fleet run.
    base_dir = tmp_path / "baseline"
    base_dir.mkdir()
    monkeypatch.chdir(base_dir)
    before = _restarts()
    run(sac_fleet_args())
    assert _restarts() == before  # healthy fleet never restarts
    baseline = _find_ckpts(base_dir)[-1]
    assert parse_ckpt_name(baseline)[0] == 32

    # Same run, but replica 1 is SIGKILLed mid-shipping (no handlers, no
    # drain — the supervisor must notice via pipe EOF and respawn it).
    chaos_dir = tmp_path / "chaos"
    chaos_dir.mkdir()
    monkeypatch.chdir(chaos_dir)
    before = _restarts()
    run(
        sac_fleet_args(
            **{
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": "[{kind: kill9, at_step: 12, replica: 1}]",
            }
        )
    )
    assert _restarts() == before + 1  # exactly one supervised restart
    faulted = _find_ckpts(chaos_dir)[-1]
    assert parse_ckpt_name(faulted)[0] == 32

    # The fault run lands on the same training position as the baseline:
    # same iteration counter, same replay write position (the learner
    # ingested exactly one full shipment per iteration either way).
    a, b = load_checkpoint(baseline), load_checkpoint(faulted)
    assert a["iter_num"] == b["iter_num"]
    assert a["rb"]._pos == b["rb"]._pos
    assert a["rb"].buffer_size == b["rb"].buffer_size


def test_ppo_fleet_kill9_shrinks_round_then_restart_rejoins(tmp_path):
    before = _restarts()
    run(
        ppo_fleet_args(
            **{
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": "[{kind: kill9, at_step: 20, replica: 0}]",
            }
        )
    )
    assert _restarts() == before + 1
    final = _find_ckpts(tmp_path)[-1]
    # The run completed every iteration despite the mid-round death: dead
    # replicas shrink a round (graceful degradation), they don't wedge it.
    assert load_checkpoint(final)["iter_num"] >= 1
    assert validate_checkpoint(final, verify_digest=True)


# ------------------------------- learner preemption + topology-elastic resume
def test_sac_fleet_sigterm_drains_then_auto_resumes_to_parity(tmp_path, monkeypatch):
    base_dir = tmp_path / "baseline"
    base_dir.mkdir()
    monkeypatch.chdir(base_dir)
    run(sac_fleet_args())
    baseline = _find_ckpts(base_dir)[-1]

    chaos_dir = tmp_path / "chaos"
    chaos_dir.mkdir()
    monkeypatch.chdir(chaos_dir)
    run(
        sac_fleet_args(
            **{
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": "[{kind: sigterm, at_step: 16}]",
            }
        )
    )
    preempt_ckpt = _find_ckpts(chaos_dir)[-1]
    assert parse_ckpt_name(preempt_ckpt)[0] == 16
    # The drain quiesced the fleet BEFORE the save: the checkpoint commit is
    # the last thing the learner did, and it fully validates.
    assert validate_checkpoint(preempt_ckpt, verify_digest=True)

    chaos.reset()
    run(sac_fleet_args(**{"checkpoint.resume_from": "auto:logs/runs"}))
    resumed = _find_ckpts(chaos_dir)[-1]
    assert parse_ckpt_name(resumed)[0] == 32

    a, b = load_checkpoint(baseline), load_checkpoint(resumed)
    assert a["iter_num"] == b["iter_num"]
    assert a["rb"]._pos == b["rb"]._pos


def test_sac_fleet_preempt_checkpoint_resumes_bit_exact_on_smaller_mesh(tmp_path, monkeypatch):
    # Preempt on the 8-device mesh with TP engaged (1024-wide stacks shard
    # over the model axis, so the recorded shardings are non-trivial).
    save_dir = tmp_path / "wide"
    save_dir.mkdir()
    monkeypatch.chdir(save_dir)
    run(
        sac_fleet_args(
            **{
                "fabric.devices": 4,
                "fabric.model_axis": 2,
                "algo.hidden_size": 1024,
                "resilience.chaos.enabled": True,
                "resilience.chaos.injectors": "[{kind: sigterm, at_step: 16}]",
            }
        )
    )
    preempt_ckpt = _find_ckpts(save_dir)[-1]
    manifest = read_manifest(preempt_ckpt)
    recorded = load_recorded_shardings(preempt_ckpt)
    assert recorded, "preemption save must record per-leaf shardings"
    assert any(
        "model" in str(rec["spec"]) for rec in recorded.values()
    ), "TP layout should appear in at least one recorded spec"
    assert int(manifest["schema_version"]) == 1  # sidecar key, same schema

    # Resume the same checkpoint on HALF the mesh: the recorded specs adapt
    # (model axis still present, data axis smaller) and the restored values
    # are the saved host payload bit for bit — then training continues to
    # the original horizon.
    chaos.reset()
    run(
        sac_fleet_args(
            **{
                "fabric.devices": 2,
                "fabric.model_axis": 2,
                "algo.hidden_size": 1024,
                "checkpoint.resume_from": "auto:logs/runs",
            }
        )
    )
    resumed = _find_ckpts(save_dir)[-1]
    assert parse_ckpt_name(resumed)[0] == 32
    assert load_checkpoint(resumed)["iter_num"] == 16

    # Bit-exact reproduction on the smaller mesh: replay the exact elastic
    # placement the resumed learner performed (recorded shardings from the
    # 8-device save, adapted to a 4-device mesh) and compare every leaf to
    # the checkpoint's host payload.
    import jax

    from sheeprl_tpu.core import mesh as mesh_lib
    from sheeprl_tpu.utils.checkpoint import place_with_recorded_shardings

    host_agent = load_checkpoint(preempt_ckpt)["agent"]
    small_mesh = mesh_lib.build_mesh(jax.devices()[:4], model_axis_size=2)
    placed = place_with_recorded_shardings(host_agent, recorded, small_mesh, prefix="agent")
    for host_leaf, placed_leaf in zip(
        jax.tree_util.tree_leaves(host_agent), jax.tree_util.tree_leaves(placed)
    ):
        np.testing.assert_array_equal(np.asarray(host_leaf), np.asarray(placed_leaf))
