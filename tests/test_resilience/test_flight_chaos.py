"""Distributed-tracing chaos scenario driven through the CLI: a chaos
``delayed_fetch`` stalls the action harvest inside the watchdog's armed
window in a supervised async-env run, the watchdog trips (on_trip=warn),
and the flight recorder writes ONE merged dump whose spans come from at
least two processes — the trainer and its forked env workers — correlated
under the run's single root trace ID. This is the acceptance scenario for
the cross-process tracing + flight-recorder subsystem."""

import glob
import json
import os

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core import chaos

pytestmark = pytest.mark.chaos

DELAY_S = 1.5
WATCHDOG_TIMEOUT_S = 0.25
INJECT_STEP = 8


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    chaos.reset()
    yield
    chaos.reset()


def _find_dumps(root):
    return sorted(
        glob.glob(os.path.join(root, "logs", "runs", "**", "flight", "flight_*.json"), recursive=True),
        key=os.path.getmtime,
    )


def test_delayed_fetch_trip_dumps_a_multiprocess_trace(tmp_path):
    run(
        [
            "exp=sac",
            "env=dummy",
            "env.id=continuous_dummy",
            "env.wrapper.id=continuous_dummy",
            "metric.log_level=1",
            "metric.log_every=4",
            "env.num_envs=2",
            # Async vector env: the env thunks run in FORKED WORKER
            # PROCESSES, which must adopt the env-var trace carrier and
            # spill their spans for the trainer's dump to merge.
            "env.sync_env=False",
            "env.capture_video=False",
            "algo.per_rank_batch_size=4",
            "algo.learning_starts=4",
            "algo.hidden_size=8",
            "algo.run_test=False",
            "algo.total_steps=48",
            "buffer.memmap=False",
            "buffer.size=64",
            "buffer.checkpoint=False",
            "checkpoint.every=0",
            "fabric.accelerator=cpu",
            "telemetry.enabled=True",
            # XLA compiles also outlive the short watchdog deadline on CPU,
            # so the dispatch guard trips too; drop the dump rate limit so
            # the delayed_fetch trip is never shadowed by a compile trip.
            "telemetry.flight.min_dump_interval_s=0.05",
            "resilience.supervisor.enabled=True",
            "resilience.watchdog.enabled=True",
            f"resilience.watchdog.timeout_s={WATCHDOG_TIMEOUT_S}",
            "resilience.watchdog.on_trip=warn",
            "resilience.chaos.enabled=True",
            "resilience.chaos.injectors="
            f"[{{kind: delayed_fetch, seconds: {DELAY_S}, at_step: {INJECT_STEP}}}]",
        ]
    )

    dumps = _find_dumps(str(tmp_path))
    assert dumps, "watchdog trip produced no flight dump"
    # Select the delayed_fetch-induced dump: its trip instant carries the
    # fetch guard's label (compile-time dispatch trips may also dump).
    doc = None
    for path in dumps:
        candidate = json.load(open(path))
        trip_evs = [
            ev for ev in candidate["traceEvents"] if ev["ph"] == "i" and ev["cat"] == "trip"
        ]
        if any(ev["args"].get("label", "").startswith("fetch/") for ev in trip_evs):
            doc = candidate
    assert doc is not None, f"no dump from the delayed_fetch trip among {dumps}"
    assert doc["reason"] == "resilience/watchdog_trip"
    assert "exceeded" in doc["message"]

    # ≥2 processes contributed SPANS (trainer + at least one env worker).
    with_spans = {pid: p for pid, p in doc["processes"].items() if p["spans"] > 0}
    assert len(with_spans) >= 2, f"single-process dump: {doc['processes']}"
    roles = {p["run_info"].get("role") for p in doc["processes"].values()}
    assert {"trainer", "env_worker"} <= roles

    # One trace ID spans ≥2 distinct pids — the run root published via the
    # env carrier and adopted by every forked worker.
    pids_by_trace = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        trace_id = (ev.get("args") or {}).get("trace_id")
        if trace_id:
            pids_by_trace.setdefault(trace_id, set()).add(ev["pid"])
    multi = {tid: pids for tid, pids in pids_by_trace.items() if len(pids) >= 2}
    assert multi, f"no trace id spans multiple processes: { {t: sorted(p) for t, p in pids_by_trace.items()} }"

    # That shared trace is the run root recorded in telemetry.jsonl's meta.
    jsonls = glob.glob(
        os.path.join(str(tmp_path), "logs", "runs", "**", "telemetry.jsonl"), recursive=True
    )
    assert jsonls
    meta = json.loads(open(jsonls[-1]).readline())
    assert meta["type"] == "meta"
    assert meta["trace_id"] in multi

    # Perfetto-loadable: a trace-event doc with only known phases, numeric
    # timestamps, and per-process track metadata.
    assert doc["displayTimeUnit"] == "ms"
    assert any(ev["ph"] == "M" and ev["name"] == "process_name" for ev in doc["traceEvents"])
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i", "M")
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], float)

    # The trip instant itself is in the ring, with the watchdog's label.
    trips = [ev for ev in doc["traceEvents"] if ev["ph"] == "i" and ev["cat"] == "trip"]
    assert any(ev["args"].get("label", "").startswith("fetch/") for ev in trips)
