"""Atomic checkpoint writer: manifest validation, crash-mid-save fallback,
corruption tolerance, rank-aware GC, and readable restore errors.

The invariant under test everywhere: there is no observable on-disk state in
which the old snapshot is gone and the new one is incomplete."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.core import chaos
from sheeprl_tpu.core.chaos import ChaosFault, corrupt_checkpoint
from sheeprl_tpu.utils.checkpoint import (
    MANIFEST_SCHEMA_VERSION,
    find_latest_valid_checkpoint,
    load_checkpoint,
    read_manifest,
    restore_opt_state,
    save_checkpoint,
    validate_checkpoint,
)


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


def _state(v=0.0):
    return {
        "agent": {
            "w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3) + v,
            "b": jnp.full((3,), v, dtype=jnp.float32),
        },
        "iter_num": 4 + int(v),
        "note": "aux-payload",
    }


def _entries(d):
    return sorted(n for n in os.listdir(d) if not n.startswith("."))


def test_save_writes_manifest_and_roundtrips(tmp_path):
    path = str(tmp_path / "ckpt_8_0.ckpt")
    save_checkpoint(path, _state())
    manifest = read_manifest(path)
    assert manifest["schema_version"] == MANIFEST_SCHEMA_VERSION
    assert manifest["step"] == 8 and manifest["rank"] == 0
    assert manifest["leaf_count"] == 2 and manifest["aux_count"] == 2
    assert validate_checkpoint(path, verify_digest=True)
    loaded = load_checkpoint(path)
    np.testing.assert_array_equal(
        np.asarray(loaded["agent"]["w"]), np.arange(6, dtype=np.float32).reshape(2, 3)
    )
    assert loaded["iter_num"] == 4 and loaded["note"] == "aux-payload"


@pytest.mark.parametrize(
    "fail_point",
    ["checkpoint.before_write", "checkpoint.before_manifest", "checkpoint.before_commit"],
)
def test_crash_mid_save_preserves_previous_snapshot(tmp_path, fail_point):
    prev = str(tmp_path / "ckpt_8_0.ckpt")
    save_checkpoint(prev, _state(0.0))
    chaos.arm_fail_point(fail_point)
    with pytest.raises(ChaosFault):
        save_checkpoint(str(tmp_path / "ckpt_16_0.ckpt"), _state(1.0))
    # The target never appeared, no staging leftovers, the previous snapshot
    # is untouched and is what the resume path finds.
    assert _entries(str(tmp_path)) == ["ckpt_8_0.ckpt"]
    assert find_latest_valid_checkpoint(str(tmp_path)) == prev
    assert validate_checkpoint(prev, verify_digest=True)


def test_resave_over_existing_path_swaps_atomically(tmp_path):
    path = str(tmp_path / "ckpt_8_0.ckpt")
    save_checkpoint(path, _state(0.0))
    save_checkpoint(path, _state(2.0))
    assert _entries(str(tmp_path)) == ["ckpt_8_0.ckpt"]
    loaded = load_checkpoint(path)
    np.testing.assert_array_equal(
        np.asarray(loaded["agent"]["b"]), np.full((3,), 2.0, np.float32)
    )


@pytest.mark.parametrize(
    "mode", ["truncate_manifest", "delete_manifest", "garbage_manifest", "delete_arrays"]
)
def test_find_latest_skips_corrupt_checkpoints(tmp_path, mode):
    older = str(tmp_path / "ckpt_8_0.ckpt")
    newer = str(tmp_path / "ckpt_16_0.ckpt")
    save_checkpoint(older, _state(0.0))
    save_checkpoint(newer, _state(1.0))
    assert find_latest_valid_checkpoint(str(tmp_path)) == newer
    corrupt_checkpoint(newer, mode)
    assert not validate_checkpoint(newer)
    assert find_latest_valid_checkpoint(str(tmp_path)) == older


def test_find_latest_rank_filter(tmp_path):
    r0 = str(tmp_path / "ckpt_8_0.ckpt")
    r1 = str(tmp_path / "ckpt_16_1.ckpt")
    save_checkpoint(r0, _state())
    save_checkpoint(r1, _state())
    assert find_latest_valid_checkpoint(str(tmp_path)) == r1
    assert find_latest_valid_checkpoint(str(tmp_path), rank=0) == r0


def test_gc_is_rank_aware(tmp_path):
    # Rank 1 saves once; rank 0 then saves a burst with keep_last=2. A global
    # sort would GC rank 1's only snapshot — the per-rank grouping must not.
    save_checkpoint(str(tmp_path / "ckpt_8_1.ckpt"), _state(), keep_last=2)
    for step in (8, 16, 24):
        save_checkpoint(str(tmp_path / f"ckpt_{step}_0.ckpt"), _state(), keep_last=2)
    assert _entries(str(tmp_path)) == ["ckpt_16_0.ckpt", "ckpt_24_0.ckpt", "ckpt_8_1.ckpt"]


def test_digest_verification_catches_tampered_aux(tmp_path):
    path = str(tmp_path / "ckpt_8_0.ckpt")
    save_checkpoint(path, _state())
    with open(os.path.join(path, "aux.pkl"), "ab") as fp:
        fp.write(b"\x00")
    assert validate_checkpoint(path)  # structurally still complete
    assert not validate_checkpoint(path, verify_digest=True)


def test_restore_opt_state_names_diverging_keypaths():
    fresh = {"mu": {"w": jnp.zeros((2,))}, "nu": {"w": jnp.zeros((2,))}}
    ckpt = {"mu": {"w": np.zeros((2,))}}
    with pytest.raises(ValueError) as exc:
        restore_opt_state(fresh, ckpt)
    msg = str(exc.value)
    assert "nu/w" in msg
    assert "missing from the checkpoint" in msg
