"""Tier-1 self-check: graftlint over the whole package.

Fails on any new, unsuppressed, non-baselined violation — this is the
machine-checked floor under every later perf/sharding PR. The second test is
the ratchet: the baseline may only shrink, so fixing a grandfathered finding
requires regenerating the file (and a PR that *adds* a finding cannot hide it
by regenerating, because this first test would still fail on its machine).
"""

import os

import pytest

from sheeprl_tpu.analysis import lint_paths
from sheeprl_tpu.analysis.baseline import (
    BASELINE_FILENAME,
    apply_baseline,
    load_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE_DIR = os.path.join(REPO_ROOT, "sheeprl_tpu")
BASELINE_PATH = os.path.join(REPO_ROOT, BASELINE_FILENAME)


@pytest.fixture(scope="module")
def scan():
    findings, files_scanned, suppressed = lint_paths([PACKAGE_DIR], root=REPO_ROOT)
    assert files_scanned > 100, "scan did not cover the package"
    return findings


@pytest.mark.graftlint
def test_no_new_violations(scan):
    baseline = load_baseline(BASELINE_PATH)
    new, _ = apply_baseline(scan, baseline)
    assert new == [], (
        "graftlint found new violation(s):\n"
        + "\n".join(f.format_text() for f in new)
        + "\nFix them, add a justified `# graftlint: disable=<ID>`, or (for "
        "pre-existing debt only) regenerate the baseline with "
        "`python -m sheeprl_tpu.analysis sheeprl_tpu/ --write-baseline`."
    )


@pytest.mark.graftlint
def test_baseline_only_shrinks(scan):
    baseline = load_baseline(BASELINE_PATH)
    _, matched = apply_baseline(scan, baseline)
    total = sum(baseline.values())
    stale = total - matched
    assert stale == 0, (
        f"{stale} baseline entr(ies) no longer match any finding — debt was "
        "paid down. Shrink the file: "
        "`python -m sheeprl_tpu.analysis sheeprl_tpu/ --write-baseline`."
    )
