"""Tier-1 self-check: graftlint over the whole package.

The debt is paid: there is no baseline file any more, and the package must
scan **clean** — zero findings, not zero-new-findings. This is the
machine-checked floor under every later perf/sharding PR. A second test
pins the baseline's retirement so it cannot quietly come back as a place
to hide new findings.
"""

import os

import pytest

from sheeprl_tpu.analysis import lint_paths
from sheeprl_tpu.analysis.baseline import BASELINE_FILENAME

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE_DIR = os.path.join(REPO_ROOT, "sheeprl_tpu")
BASELINE_PATH = os.path.join(REPO_ROOT, BASELINE_FILENAME)

PROJECT_RULE_IDS = (
    "GL009",
    "GL010",
    "GL011",
    "GL012",
    "GL013",
    "GL014",
    "GL015",
    "GL016",
    "GL017",
    "GL018",
)


@pytest.fixture(scope="module")
def scan():
    findings, files_scanned, suppressed = lint_paths([PACKAGE_DIR], root=REPO_ROOT)
    assert files_scanned > 100, "scan did not cover the package"
    return findings


@pytest.mark.graftlint
def test_package_is_clean(scan):
    assert scan == [], (
        "graftlint found violation(s):\n"
        + "\n".join(f.format_text() for f in scan)
        + "\nFix them or add a justified `# graftlint: disable=<ID>`. There "
        "is no baseline to hide behind any more."
    )


@pytest.mark.graftlint
def test_baseline_stays_retired():
    assert not os.path.exists(BASELINE_PATH), (
        f"{BASELINE_FILENAME} reappeared at the repo root. The baseline was "
        "burned down and deleted; new findings must be fixed or suppressed "
        "with a justification, not grandfathered."
    )


@pytest.mark.graftlint
def test_project_rules_clean_on_live_repo(scan):
    """GL009-GL018 specifically report nothing on the live package."""
    offenders = [f for f in scan if f.rule in PROJECT_RULE_IDS]
    assert offenders == [], "\n".join(f.format_text() for f in offenders)


@pytest.mark.graftlint
def test_shardlint_pack_landed_at_zero():
    """The mesh/collective pack (GL014-GL018) landed with zero findings AND
    zero suppressions on the live package: the scale-out rules must start
    from a clean slate, with nothing grandfathered behind a disable."""
    from sheeprl_tpu.analysis.registry import all_rules

    pack = {"GL014", "GL015", "GL016", "GL017", "GL018"}
    assert pack <= {r.id for r in all_rules()}
    findings, _, _ = lint_paths([PACKAGE_DIR], root=REPO_ROOT, rules=sorted(pack))
    assert findings == [], "\n".join(f.format_text() for f in findings)
    for base, _, names in os.walk(PACKAGE_DIR):
        for name in names:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(base, name), "r", encoding="utf-8") as fh:
                text = fh.read()
            for rule_id in sorted(pack):
                assert f"disable={rule_id}" not in text, (
                    f"{name} suppresses {rule_id}; the pack must land "
                    "suppression-free"
                )
