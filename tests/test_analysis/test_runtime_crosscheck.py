"""Shardlint verdicts cross-checked against runtime reality.

Each source string below is fed to the analyzer AND executed on the
virtual 8-device CPU platform (tests/conftest.py forces
``--xla_force_host_platform_device_count=8``). The rule's prediction must
match what actually happens:

- GL015 (unbound collective): the analyzer flags it, and tracing the same
  program raises the unbound-axis ``NameError``; the shard_map-bound twin
  is silent AND computes the cross-shard mean.
- GL017 (un-split key): the analyzer flags it, and running the same
  program produces *identical* randomness on every shard; the
  ``fold_in(axis_index)`` twin is silent AND the shards diverge.

This pins the static rules to observed device semantics, so a rule can
never drift into flagging healthy programs (or blessing broken ones)
without this file failing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sheeprl_tpu.analysis import lint_source

DEVICES = jax.devices()

pytestmark = pytest.mark.skipif(
    len(DEVICES) < 2,
    reason="needs the virtual multi-device CPU platform from tests/conftest.py",
)

# The exec namespace supplies DEVICES (a real device array is meaningless to
# the analyzer, which only reads the axis-names literal).
GL015_UNBOUND_SRC = """\
import jax
from jax.sharding import Mesh

mesh = Mesh(DEVICES, ("data",))


@jax.jit
def sync_grads(grads):
    return jax.lax.pmean(grads, "data")
"""

GL015_BOUND_SRC = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(DEVICES, ("data",))


def mean_grads(grads):
    return jax.lax.pmean(grads, "data")


sync_grads = shard_map(mean_grads, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
"""

GL017_LOCKSTEP_SRC = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(DEVICES, ("data",))


def sample(key, x):
    return x + jax.random.normal(key, x.shape)


sampler = shard_map(sample, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))
"""

GL017_FOLDED_SRC = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(DEVICES, ("data",))


def sample(key, x):
    key = jax.random.fold_in(key, jax.lax.axis_index("data"))
    return x + jax.random.normal(key, x.shape)


sampler = shard_map(sample, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))
"""


def _verdict(src):
    findings, _ = lint_source(src, path="crosscheck.py")
    return sorted({f.rule for f in findings})


def _execute(src):
    namespace = {"DEVICES": np.array(DEVICES)}
    exec(compile(src, "crosscheck.py", "exec"), namespace)
    return namespace


def _shard_rows(fn):
    n = len(DEVICES)
    out = np.asarray(fn(jax.random.PRNGKey(0), jnp.zeros((n, 3))))
    return [out[i] for i in range(n)]


def test_gl015_flagged_program_fails_at_trace_time():
    assert _verdict(GL015_UNBOUND_SRC) == ["GL015"]
    ns = _execute(GL015_UNBOUND_SRC)
    with pytest.raises(NameError, match="unbound axis name"):
        ns["sync_grads"](jnp.ones(len(DEVICES)))


def test_gl015_silent_program_reduces_across_shards():
    assert _verdict(GL015_BOUND_SRC) == []
    ns = _execute(GL015_BOUND_SRC)
    grads = jnp.arange(float(len(DEVICES)))
    result = np.asarray(ns["sync_grads"](grads))
    assert np.allclose(result, float(np.mean(np.arange(len(DEVICES)))))


def test_gl017_flagged_program_samples_in_lockstep():
    """The hazard GL017 names is real: a replicated, un-split key makes
    every shard draw the SAME noise."""
    assert _verdict(GL017_LOCKSTEP_SRC) == ["GL017"]
    rows = _shard_rows(_execute(GL017_LOCKSTEP_SRC)["sampler"])
    assert all(np.allclose(rows[0], row) for row in rows[1:])


def test_gl017_silent_program_samples_divergently():
    """fold_in(axis_index(...)) is the sanctioned fix, and it works: shards
    draw distinct noise, and the analyzer stays quiet."""
    assert _verdict(GL017_FOLDED_SRC) == []
    rows = _shard_rows(_execute(GL017_FOLDED_SRC)["sampler"])
    assert not any(np.allclose(rows[0], row) for row in rows[1:])
