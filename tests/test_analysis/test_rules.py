"""Per-rule fixture corpus: every rule has a true-positive file that must
fire and a clean/suppressed file that must stay silent."""

import os

import pytest

from sheeprl_tpu.analysis import lint_file

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

ALL_RULE_IDS = ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007", "GL008")


def _lint_fixture(name):
    return lint_file(os.path.join(FIXTURES, name))


@pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
def test_positive_fixture_fires(rule_id):
    findings, _ = _lint_fixture(f"{rule_id.lower()}_positive.py")
    fired = {f.rule for f in findings}
    assert rule_id in fired, f"{rule_id} did not fire on its positive fixture"


@pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
def test_clean_fixture_is_silent(rule_id):
    findings, suppressed = _lint_fixture(f"{rule_id.lower()}_clean.py")
    assert findings == [], [f.format_text() for f in findings]
    # Every clean fixture demonstrates the suppression syntax at least once.
    assert suppressed >= 1


def test_every_positive_line_is_annotated():
    """Positive fixtures mark expected violations with `<- GLnnn`; the rule
    must flag each annotated line (keeps fixtures and rules honest)."""
    for rule_id in ALL_RULE_IDS:
        name = f"{rule_id.lower()}_positive.py"
        path = os.path.join(FIXTURES, name)
        with open(path, "r", encoding="utf-8") as fh:
            expected = {
                lineno
                for lineno, line in enumerate(fh, start=1)
                if f"<- {rule_id}" in line
            }
        findings, _ = _lint_fixture(name)
        flagged = {f.line for f in findings if f.rule == rule_id}
        missing = expected - flagged
        assert not missing, f"{name}: annotated lines not flagged: {sorted(missing)}"


def test_gl001_split_consumes_parent():
    findings, _ = _lint_fixture("gl001_positive.py")
    assert any("split" in f.message for f in findings if f.rule == "GL001")


def test_gl002_distinguishes_jit_and_host_tiers():
    findings, _ = _lint_fixture("gl002_positive.py")
    messages = [f.message for f in findings if f.rule == "GL002"]
    assert any("jit-traced" in m for m in messages)
    assert any("host loop" in m or "host-side" in m for m in messages)


def test_gl003_flags_the_seed_bug_line():
    """The exact pre-fix line from sheeprl_tpu/parallel/ring_attention.py:25."""
    from sheeprl_tpu.analysis import lint_source

    findings, _ = lint_source("from jax import shard_map\n", path="ring_attention.py")
    assert [f.rule for f in findings] == ["GL003"]
    assert "jax.experimental.shard_map" in findings[0].message


def test_gl004_static_argnames_branching_is_allowed():
    findings, _ = _lint_fixture("gl004_clean.py")
    assert findings == []


def test_gl005_rebinding_result_is_allowed():
    findings, _ = _lint_fixture("gl005_clean.py")
    assert findings == []


def test_gl006_needs_the_interact_import():
    """The rule only fires where the async helper is actually available —
    the same loop without the import is GL002 territory, not GL006."""
    from sheeprl_tpu.analysis import lint_source

    src = (
        "import jax\n"
        "def rollout(envs, policy, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        out = policy(obs)\n"
        "        acts = jax.device_get(out)  # graftlint: disable=GL002\n"
        "        obs, *_ = envs.step(acts)\n"
    )
    findings, _ = lint_source(src, path="no_import.py")
    assert not any(f.rule == "GL006" for f in findings)
    findings, _ = lint_source(
        "from sheeprl_tpu.core import interact  # noqa: F401\n" + src, path="with_import.py"
    )
    assert any(f.rule == "GL006" for f in findings)


def test_gl006_ignores_host_arrays_and_code_outside_the_loop():
    findings, _ = _lint_fixture("gl006_clean.py")
    assert findings == []
