"""Per-rule fixture corpus: every rule has a true-positive file that must
fire and a clean/suppressed file that must stay silent."""

import os

import pytest

from sheeprl_tpu.analysis import lint_file, lint_paths

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

# Single-file fixtures. GL009/GL011 are inherently multi-file (cross-module
# donation, code-vs-YAML drift) and live in fixture *directories* below, as
# do GL014 (axis constants resolved across imports) and GL018 (producer and
# consumer modules disagreeing on a sharding).
ALL_RULE_IDS = (
    "GL001",
    "GL002",
    "GL003",
    "GL004",
    "GL005",
    "GL006",
    "GL007",
    "GL008",
    "GL010",
    "GL012",
    "GL013",
    "GL015",
    "GL016",
    "GL017",
)
DIR_RULE_IDS = ("GL009", "GL011", "GL014", "GL018")


def _lint_fixture(name):
    return lint_file(os.path.join(FIXTURES, name))


@pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
def test_positive_fixture_fires(rule_id):
    findings, _ = _lint_fixture(f"{rule_id.lower()}_positive.py")
    fired = {f.rule for f in findings}
    assert rule_id in fired, f"{rule_id} did not fire on its positive fixture"


@pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
def test_clean_fixture_is_silent(rule_id):
    findings, suppressed = _lint_fixture(f"{rule_id.lower()}_clean.py")
    assert findings == [], [f.format_text() for f in findings]
    # Every clean fixture demonstrates the suppression syntax at least once.
    assert suppressed >= 1


def test_every_positive_line_is_annotated():
    """Positive fixtures mark expected violations with `<- GLnnn`; the rule
    must flag each annotated line (keeps fixtures and rules honest)."""
    for rule_id in ALL_RULE_IDS:
        name = f"{rule_id.lower()}_positive.py"
        path = os.path.join(FIXTURES, name)
        with open(path, "r", encoding="utf-8") as fh:
            expected = {
                lineno
                for lineno, line in enumerate(fh, start=1)
                if f"<- {rule_id}" in line
            }
        findings, _ = _lint_fixture(name)
        flagged = {f.line for f in findings if f.rule == rule_id}
        missing = expected - flagged
        assert not missing, f"{name}: annotated lines not flagged: {sorted(missing)}"


def test_gl001_split_consumes_parent():
    findings, _ = _lint_fixture("gl001_positive.py")
    assert any("split" in f.message for f in findings if f.rule == "GL001")


def test_gl002_distinguishes_jit_and_host_tiers():
    findings, _ = _lint_fixture("gl002_positive.py")
    messages = [f.message for f in findings if f.rule == "GL002"]
    assert any("jit-traced" in m for m in messages)
    assert any("host loop" in m or "host-side" in m for m in messages)


def test_gl003_flags_the_seed_bug_line():
    """The exact pre-fix line from sheeprl_tpu/parallel/ring_attention.py:25."""
    from sheeprl_tpu.analysis import lint_source

    findings, _ = lint_source("from jax import shard_map\n", path="ring_attention.py")
    assert [f.rule for f in findings] == ["GL003"]
    assert "jax.experimental.shard_map" in findings[0].message


def test_gl004_static_argnames_branching_is_allowed():
    findings, _ = _lint_fixture("gl004_clean.py")
    assert findings == []


def test_gl005_rebinding_result_is_allowed():
    findings, _ = _lint_fixture("gl005_clean.py")
    assert findings == []


def test_gl006_needs_the_interact_import():
    """The rule only fires where the async helper is actually available —
    the same loop without the import is GL002 territory, not GL006."""
    from sheeprl_tpu.analysis import lint_source

    src = (
        "import jax\n"
        "def rollout(envs, policy, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        out = policy(obs)\n"
        "        acts = jax.device_get(out)  # graftlint: disable=GL002\n"
        "        obs, *_ = envs.step(acts)\n"
    )
    findings, _ = lint_source(src, path="no_import.py")
    assert not any(f.rule == "GL006" for f in findings)
    findings, _ = lint_source(
        "from sheeprl_tpu.core import interact  # noqa: F401\n" + src, path="with_import.py"
    )
    assert any(f.rule == "GL006" for f in findings)


def test_gl006_ignores_host_arrays_and_code_outside_the_loop():
    findings, _ = _lint_fixture("gl006_clean.py")
    assert findings == []


# --------------------------------------------------------- directory fixtures
def _lint_dir(name):
    return lint_paths([os.path.join(FIXTURES, name)])


def _annotated_lines(dirname, rule_id):
    expected = {}
    root = os.path.join(FIXTURES, dirname)
    for base, _, names in os.walk(root):
        for name in sorted(names):
            if not name.endswith((".py", ".yaml")):
                continue
            path = os.path.join(base, name)
            with open(path, "r", encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, start=1):
                    if f"<- {rule_id}" in line:
                        expected.setdefault(name, set()).add(lineno)
    return expected


@pytest.mark.parametrize("rule_id", DIR_RULE_IDS)
def test_positive_dir_fixture_fires_on_annotated_lines(rule_id):
    dirname = f"{rule_id.lower()}_positive"
    findings, _, _ = _lint_dir(dirname)
    flagged = {}
    for f in findings:
        if f.rule == rule_id:
            flagged.setdefault(os.path.basename(f.path), set()).add(f.line)
    expected = _annotated_lines(dirname, rule_id)
    assert expected, f"{dirname} has no `<- {rule_id}` annotations"
    for name, lines in expected.items():
        missing = lines - flagged.get(name, set())
        assert not missing, f"{dirname}/{name}: annotated lines not flagged: {sorted(missing)}"
    assert {f.rule for f in findings} == {rule_id}, [f.format_text() for f in findings]


@pytest.mark.parametrize("rule_id", DIR_RULE_IDS)
def test_clean_dir_fixture_is_silent(rule_id):
    findings, _, suppressed = _lint_dir(f"{rule_id.lower()}_clean")
    assert findings == [], [f.format_text() for f in findings]
    assert suppressed >= 1


def test_gl009_does_not_double_report_with_gl005():
    """Cross-module sites are GL009's; GL005 must stay quiet on them."""
    findings, _, _ = _lint_dir("gl009_positive")
    assert not any(f.rule == "GL005" for f in findings)


def test_gl011_reports_both_drift_directions():
    findings, _, _ = _lint_dir("gl011_positive")
    by_ext = {os.path.splitext(f.path)[1] for f in findings if f.rule == "GL011"}
    assert by_ext == {".py", ".yaml"}, "expected an unknown read AND a dead YAML key"


def test_gl011_chained_alias_resolves_nested_groups():
    """`perf = tele.get("perf") or {}` after `tele = cfg.telemetry` makes
    `perf.get("harvest_window")` track `telemetry.perf.harvest_window` —
    the drifted nested read must flag under its FULL dotted path, and the
    resolving reads through the same chain must stay silent."""
    findings, _, _ = _lint_dir("gl011_positive")
    messages = [f.message for f in findings if f.rule == "GL011"]
    assert any("telemetry.perf.harvest_window" in m for m in messages)
    assert not any("telemetry.perf.enabled" in m for m in messages)


def test_gl011_knows_the_telemetry_perf_keys():
    """The live repo's config model carries the performance-observatory
    group: every `telemetry.perf.*` key the Telemetry facade reads must
    resolve, so goodput-accounting configs can never silently drift."""
    import sheeprl_tpu
    from sheeprl_tpu.analysis.configmodel import ConfigModel

    root = os.path.join(os.path.dirname(sheeprl_tpu.__file__), "configs")
    model = ConfigModel.load(root)
    for key in ("enabled", "probe", "peak_flops", "peak_hbm_gbps"):
        assert model.resolves(f"telemetry.perf.{key}"), key
