"""Baseline semantics: entries match on content, consume one-for-one, and the
checked-in file only ever shrinks."""

import json
import os

from sheeprl_tpu.analysis.baseline import (
    BASELINE_FILENAME,
    apply_baseline,
    discover_baseline,
    load_baseline,
    save_baseline,
)
from sheeprl_tpu.analysis.finding import Finding


def _finding(line=1, snippet="x.item()", rule="GL002", path="a.py"):
    return Finding(rule=rule, path=path, line=line, col=1, message="m", snippet=snippet)


def test_roundtrip_and_line_number_independence(tmp_path):
    baseline_file = str(tmp_path / BASELINE_FILENAME)
    save_baseline(baseline_file, [_finding(line=10)])
    # Same content at a different line still matches: edits above a
    # grandfathered finding must not invalidate the baseline.
    new, matched = apply_baseline([_finding(line=99)], load_baseline(baseline_file))
    assert new == [] and matched == 1


def test_entries_consume_one_for_one(tmp_path):
    baseline_file = str(tmp_path / BASELINE_FILENAME)
    save_baseline(baseline_file, [_finding()])
    # A second identical violation is NEW even though one is baselined.
    new, matched = apply_baseline(
        [_finding(line=5), _finding(line=50)], load_baseline(baseline_file)
    )
    assert matched == 1
    assert len(new) == 1


def test_different_rule_or_path_does_not_match(tmp_path):
    baseline_file = str(tmp_path / BASELINE_FILENAME)
    save_baseline(baseline_file, [_finding()])
    baseline = load_baseline(baseline_file)
    assert apply_baseline([_finding(rule="GL001")], baseline)[0] != []
    assert apply_baseline([_finding(path="b.py")], baseline)[0] != []


def test_discover_walks_up(tmp_path):
    root = tmp_path / "repo"
    nested = root / "pkg" / "sub"
    nested.mkdir(parents=True)
    save_baseline(str(root / BASELINE_FILENAME), [])
    assert discover_baseline(str(nested)) == str(root / BASELINE_FILENAME)


def test_baseline_file_schema(tmp_path):
    baseline_file = str(tmp_path / BASELINE_FILENAME)
    save_baseline(baseline_file, [_finding()])
    with open(baseline_file, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    assert payload["schema_version"] == 1
    assert payload["tool"] == "graftlint"
    assert payload["entries"] == [{"rule": "GL002", "path": "a.py", "snippet": "x.item()"}]


def test_repo_carries_no_baseline():
    """The grandfathered debt was paid down and the checked-in baseline
    deleted; the mechanism stays (for downstream users), the file must not."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    baseline_path = os.path.join(repo_root, BASELINE_FILENAME)
    assert not os.path.exists(baseline_path), "graftlint baseline must stay retired"
