"""Spec and collective sites: one typo'd axis, one inconsistent spelling."""

import jax
from jax.sharding import PartitionSpec as P

from gl014_positive.axes import DATA_AXIS

BATCH_SPEC = P(DATA_AXIS)  # resolves through the imported constant: fine
STALE_SPEC = P(None, "dat")  # <- GL014


def mean_over_replicas(x):
    return jax.lax.pmean(x, "Data")  # <- GL014
