"""True positives for GL012: host side effects reachable from a jit boundary.

Neither helper is decorated; both are in the jit closure because
`train_step` (jitted) calls `_inner_step`, which calls them.
"""

import time

import jax
import jax.numpy as jnp


def _log_shape(x):
    print("tracing with", x.shape)  # <- GL012


def _stamp(metrics):
    metrics["wall"] = time.time()  # <- GL012
    return metrics


def _inner_step(params, batch):
    _log_shape(batch)
    loss = jnp.mean(batch)
    return _stamp({"loss": loss})


@jax.jit
def train_step(params, batch):
    return _inner_step(params, batch)
