"""Clean for GL013: the factory idiom — captured once, never rebound."""

import jax


def make_step(cfg):
    lr = cfg["lr"]

    @jax.jit
    def step(params, grads):
        return params - lr * grads

    return step


def warmup(params):
    scale = 1.0

    @jax.jit
    def apply(x):  # graftlint: disable=GL013
        return x * scale

    # The rebind happens before `apply` is ever called, so the capture the
    # trace sees is the final value; suppressed with that justification.
    scale = 0.5
    return apply(params)
