"""GL016 clean: collective gating is host-uniform (step counter), fetched
values only guard local work, and the one deliberate gate is suppressed."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(None, ("data",))


def all_reduce(state):
    fn = shard_map(lambda x: x, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    return fn(state)


def train_loop(state, step, sync_every):
    if step % sync_every == 0:  # host-uniform counter: every host agrees
        state = all_reduce(state)
    return state


def log_maybe(logger, loss):
    loss_now = float(jax.device_get(loss))
    if loss_now > 100.0:  # fetched, but guards no collective
        logger.warning("loss spike: %s", loss_now)
    return loss_now


def force_sync(state, flag):
    # Single-host debug path; hosts cannot disagree by construction.
    if jax.device_get(flag):  # graftlint: disable=GL016
        state = all_reduce(state)
    return state
