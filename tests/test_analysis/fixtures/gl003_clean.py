"""GL003 clean: version-stable imports, plus one suppressed forward-compat."""

from jax import jit, vmap  # allowlisted on the pinned minimum jax
from jax.experimental.shard_map import shard_map  # stable home
from jax.sharding import Mesh, PartitionSpec

try:
    from jax.experimental.shard_map import shard_map as _sm
except ImportError:
    from jax import shard_map as _sm  # graftlint: disable=GL003
