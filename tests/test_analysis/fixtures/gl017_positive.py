"""GL017 positive: a replicated (un-split) PRNG key consumed raw inside a
data-sharded shard_map body — every shard draws identical randomness."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(None, ("data",))


def sample(key, x):
    return x + jax.random.normal(key, x.shape)


sampler = shard_map(sample, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))  # <- GL017
