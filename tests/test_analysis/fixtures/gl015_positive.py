"""GL015 positives: an unbound collective and a bound-but-never-reduced axis."""

import jax
from jax.sharding import Mesh, PartitionSpec as P  # noqa: F401

mesh = Mesh(None, ("data",))


@jax.jit
def sync_grads(grads):
    return jax.lax.pmean(grads, "data")  # <- GL015


def scale(x):
    return x * 2.0


batched_scale = jax.vmap(scale, axis_name="batch")  # <- GL015
