"""GL001 true positives: the same key feeds two consumers."""

import jax


def double_consume(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # <- GL001: key already consumed
    return a + b


def parent_after_split(key):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (4,))
    y = jax.random.normal(key, (4,))  # <- GL001: parent consumed by split
    return x + y + jax.random.normal(k2, (4,))


def reuse_in_loop(key):
    total = 0.0
    for _ in range(8):
        total += jax.random.normal(key, ())  # <- GL001 across iterations
    return total
