"""GL007 clean: the sanctioned atomic shape — stage into a temp sibling,
fsync, commit with one rename — plus reads, appends, and a suppressed
deliberate in-place write."""

import json
import os
import pickle
import shutil


def save_checkpoint_atomically(ckpt_path, ckptr, arrays, aux, manifest):
    parent = os.path.dirname(ckpt_path)
    staging = os.path.join(parent, f".tmp-{os.path.basename(ckpt_path)}")
    ckptr.save(os.path.join(staging, "arrays"), arrays)
    with open(os.path.join(staging, "aux.pkl"), "wb") as fp:
        pickle.dump(aux, fp)
        fp.flush()
        os.fsync(fp.fileno())
    with open(os.path.join(staging, "manifest.json"), "w") as fp:
        json.dump(manifest, fp)
        fp.flush()
        os.fsync(fp.fileno())
    os.rename(staging, ckpt_path)


def gc_trash(parent, trash_dir):
    # Deleting a commit-swap leftover writes nothing afterwards: not a
    # delete-then-write window.
    shutil.rmtree(os.path.join(parent, trash_dir), ignore_errors=True)


def read_and_append(ckpt_path, event):
    with open(os.path.join(ckpt_path, "manifest.json")) as fp:
        manifest = json.load(fp)
    # Append-only event logs are a legitimate non-atomic format.
    with open(os.path.join(ckpt_path, "events.jsonl"), "a") as fp:
        fp.write(json.dumps(event) + "\n")
    return manifest


def write_scratch_marker(ckpt_path, payload):
    # A deliberate, documented in-place write (crash marker whose torn state
    # is itself the signal) may be suppressed explicitly.
    with open(ckpt_path + ".crashed", "w") as fp:  # graftlint: disable=GL007
        fp.write(payload)
