"""A jit train step whose declared input layout matches its producers."""

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(None, ("data", "model"))


@partial(jax.jit, in_shardings=(P("data"),))
def train_step(batch):
    return batch * 2.0
