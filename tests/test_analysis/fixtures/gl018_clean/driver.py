"""Producers that agree with the consumer's declared layout; the one
intentional reshard (a layout migration step) is suppressed."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from gl018_clean.pipeline import mesh, train_step


def run(batch):
    batch = jax.device_put(batch, NamedSharding(mesh, P("data")))
    return train_step(batch)


def run_migrating(batch):
    batch = jax.device_put(batch, NamedSharding(mesh, P("model")))
    return train_step(batch)  # graftlint: disable=GL018
