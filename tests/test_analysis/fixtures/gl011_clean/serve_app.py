"""Every read resolves; the one unread YAML key carries a justified
suppression (`reserved_slot` is kept for parity with an upstream config).
The telemetry block demonstrates the chained-alias idioms the rule
unwraps: `<chain> if cond else None` and `<chain> or {}` both register the
alias, so the nested `telemetry.perf.*` leaves are tracked precisely."""


def main(cfg):
    total = cfg.num_steps
    tag = cfg.run_name
    lr = cfg.algo.lr
    mom = cfg.algo.get("momentum", 0.9)
    tele = cfg.get("telemetry") if hasattr(cfg, "get") else None
    perf = tele.get("perf") or {}
    armed = perf.get("enabled")
    probing = perf.get("probe", True)
    return total, tag, lr, mom, armed, probing
