"""Every read resolves; the one unread YAML key carries a justified
suppression (`reserved_slot` is kept for parity with an upstream config)."""


def main(cfg):
    total = cfg.num_steps
    tag = cfg.run_name
    lr = cfg.algo.lr
    mom = cfg.algo.get("momentum", 0.9)
    return total, tag, lr, mom
