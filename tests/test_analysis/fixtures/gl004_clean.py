"""GL004 clean: in-graph control flow, hashable statics, one suppressed."""

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def branch_in_graph(x, threshold):
    return jnp.where(threshold > 0, x + 1, x - 1)


@partial(jax.jit, static_argnames=("mode",))
def static_branch(x, mode):
    if mode == "double":  # static arg: branch resolves at trace time
        return x * 2
    return x


@jax.jit
def none_check_is_static(x, mask):
    if mask is None:  # `is None` resolves without concretizing
        return x
    return x * mask


@partial(jax.jit, static_argnames=("sizes",))
def reshape_to(x, sizes):
    return x.reshape(sizes)


def caller(x):
    return reshape_to(x, sizes=(2, 2))


def caller_suppressed(x):
    return reshape_to(x, sizes=[2, 2])  # graftlint: disable=GL004
