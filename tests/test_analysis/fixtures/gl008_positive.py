"""GL008 true positives: spans that vanish (or leak their trace context) the
moment the guarded region raises — discarded span calls, manual __enter__
without a finally-guarded __exit__, and spans bound but never entered."""


def discarded_span(tracer, payload):
    # Bare call: the context manager is never entered, nothing records.
    tracer.span("rollout/ship", "transfer")  # <- GL008
    ship(payload)


def manual_enter_unguarded_exit(tracer, batch):
    # An exception in train() skips __exit__: the span never reaches the
    # ring AND the child trace context stays installed for the thread.
    span = tracer.span("train/step", "train")  # <- GL008
    span.__enter__()
    loss = train(batch)
    span.__exit__(None, None, None)
    return loss


def exit_outside_finally(telemetry, fn):
    # The except clause only covers ValueError; any other exception leaks.
    cm = telemetry.span("io/save")  # <- GL008
    cm.__enter__()
    try:
        fn()
    except ValueError:
        pass
    cm.__exit__(None, None, None)


def bound_and_dropped(self_tracer_holder, n):
    pending = self_tracer_holder.tracer.span("fetch/harvest", "transfer")  # <- GL008
    for _ in range(n):
        poll()
    return n


def ship(payload):
    return payload


def train(batch):
    return batch


def poll():
    return None
