"""GL002 clean: device-resident metrics, one coalesced fetch per interval."""

import jax
import jax.numpy as jnp


@jax.jit
def loss_stays_on_device(x):
    return jnp.mean(x**2)


def train_loop(step_fn, state, batches, log_every=100):
    pending = []
    for i, batch in enumerate(batches):
        state, loss = step_fn(state, batch)
        pending.append(loss)
        if (i + 1) % log_every == 0:
            # One coalesced transfer for the whole interval: the sanctioned
            # pattern, opted out explicitly.
            fetched = jax.device_get(pending)  # graftlint: disable=GL002
            pending.clear()
            yield fetched
    return state


def fetch_after_loop(outputs):
    return jax.device_get(outputs)
