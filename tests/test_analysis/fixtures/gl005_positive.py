"""GL005 true positive: donated buffer read after the jitted call."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def use_after_donate(state, batch):
    new_state = step(state, batch)
    drift = new_state - state  # <- GL005: `state` was donated to step()
    return new_state, drift
