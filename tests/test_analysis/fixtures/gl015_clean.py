"""GL015 clean: every collective is bound by a shard_map on its call path,
and the one deliberately-unbound helper is suppressed."""

import jax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover - pinned-range fallback
    shard_map = None

mesh = Mesh(None, ("data",))


def mean_grads(grads):
    return jax.lax.pmean(grads, "data")


def make_step():
    return shard_map(mean_grads, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))


def lonely_mean(x):
    # Traced only under an external harness that carries the axis.
    return jax.lax.pmean(x, "data")  # graftlint: disable=GL015
