"""GL006 clean: actions ride the async pipeline, host-resident arrays stay
unflagged, and the one knowingly-synchronous debug fetch is suppressed."""

import jax
import numpy as np

from sheeprl_tpu.core.interact import InteractionPipeline


def rollout(envs, policy_fn, params, obs, steps, pipeline: InteractionPipeline):
    # The sanctioned shape: submit at dispatch, harvest just before step.
    for _ in range(steps):
        actions_j = policy_fn(params, obs)
        pending = pipeline.fetch(actions_j, label="player_actions")
        actions = pending.harvest()
        obs, reward, term, trunc, info = envs.step(actions)
    return obs


def replay_rollout(envs, recorded_actions, steps):
    # Host-resident actions: nothing in flight, nothing to overlap.
    for t in range(steps):
        acts = np.asarray(recorded_actions[t])
        envs.step(acts)


def fetch_after_rollout(outputs):
    # One coalesced fetch outside any interaction loop.
    return jax.device_get(outputs)


def debug_rollout(envs, policy_fn, params, obs, steps):
    for _ in range(steps):
        out = policy_fn(params, obs)
        # Deliberately synchronous: isolates device errors to the step
        # that produced them while debugging NaNs.
        acts = jax.device_get(out)  # graftlint: disable=GL006,GL002
        obs, *_ = envs.step(acts)
    return obs
