"""GL008 clean: the sanctioned span shapes — `with` blocks, passthrough
returns, ExitStack registration, manual enter with a finally-guarded exit —
plus one suppressed deliberate fire-and-forget."""

import contextlib


def with_block(tracer, batch):
    # The canonical shape: __exit__ runs (and the parent trace context is
    # restored) even when the body raises.
    with tracer.span("train/step", "train"):
        return train(batch)


def named_then_with(tracer, fn):
    span = tracer.span("rollout/ship", "transfer")
    with span:
        fn()


def passthrough_helper(telemetry, name, category):
    # Facade passthrough (Telemetry.span): the caller owns the lifecycle.
    return telemetry.span(name, category)


def exitstack_owned(tracer, fns):
    with contextlib.ExitStack() as stack:
        stack.enter_context(tracer.span("loop", "host"))
        for fn in fns:
            fn()


def manual_enter_finally_exit(tracer, fn):
    # Manual protocol is fine when the close is exception-proof.
    span = tracer.span("guarded", "host")
    span.__enter__()
    try:
        fn()
    finally:
        span.__exit__(None, None, None)


def deliberate_marker(tracer):
    # A span deliberately abandoned (e.g. probing tracer liveness in a
    # diagnostic) may be suppressed explicitly.
    tracer.span("probe")  # graftlint: disable=GL008
    return True


def not_a_tracer(grid):
    # A domain object with a `span` method is out of scope: no tracer-ish
    # receiver, no finding.
    grid.span(3, 4)
    return grid


def train(batch):
    return batch
