"""Clean for GL010: every mutation holds the declared lock or is exempt."""

import threading

_counter = 0  # graftlint: guarded-by(_counter_lock)
_counter_lock = threading.Lock()


def bump():
    global _counter
    with _counter_lock:
        _counter += 1


class Registry:
    def __init__(self):
        self._lock = threading.RLock()
        # __init__ is exempt: construction is single-threaded.
        self._metrics = {}  # graftlint: guarded-by(self._lock)

    def record(self, name, value):
        with self._lock:
            self._metrics[name] = value

    def _flush_locked(self):
        # Caller-holds-lock convention, named into the signature.
        self._metrics.clear()

    def seed(self, name):
        # Called before any worker thread starts; the race cannot happen.
        self._metrics[name] = 0.0  # graftlint: disable=GL010
