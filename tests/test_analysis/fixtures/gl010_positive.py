"""True positives for GL010: mutating guarded-by state without the lock."""

import threading

_pending = []  # graftlint: guarded-by(_queue_lock)
_queue_lock = threading.Lock()


def enqueue(item):
    _pending.append(item)  # <- GL010


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._sessions = {}  # graftlint: guarded-by(self._lock)
        self._closed = False  # graftlint: guarded-by(self._lock)

    def open_session(self, sid):
        self._sessions[sid] = object()  # <- GL010

    def close(self):
        self._closed = True  # <- GL010
        with self._lock:
            self._sessions.clear()
