"""Cross-module use-after-donate: both import spellings."""

from gl009_positive import steps
from gl009_positive.steps import train_step


def run(state, batches):
    for batch in batches:
        new_state = train_step(state, batch)
        log_norm(state)  # <- GL009
        state = new_state
    return state


def run_once(state, batch):
    out = steps.train_step(state, batch)
    norm = state.sum()  # <- GL009
    return out, norm


def log_norm(x):
    return x
