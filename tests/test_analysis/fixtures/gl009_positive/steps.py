"""Donating train step; the hazardous call sites live in driver.py."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    return state + batch
