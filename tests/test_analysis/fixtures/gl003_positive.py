"""GL003 true positives, including the exact seed bug from
sheeprl_tpu/parallel/ring_attention.py:25 (pre-fix)."""

from jax import shard_map  # <- GL003: not in pinned jax 0.4.37
from jax import tree_map  # <- GL003: removed from jax top level
