"""Producer placing the batch under a sharding the consumer disagrees with."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from gl018_positive.pipeline import mesh, train_step


def run(batch):
    batch = jax.device_put(batch, NamedSharding(mesh, P("data")))
    return train_step(batch)  # <- GL018
