"""A jit train step that declares its input layout via in_shardings."""

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(None, ("data", "model"))


@partial(jax.jit, in_shardings=(P("model"),))
def train_step(batch):
    return batch * 2.0
