"""Clean cross-module donation: the result is rebound over the donated name."""

from gl009_clean.steps import train_step


def run(state, batches):
    for batch in batches:
        state = train_step(state, batch)
    return state


def profiled(state, batch):
    out = train_step(state, batch)
    # Deliberate: this path feeds host-resident numpy arrays, which jax
    # copies instead of donating, so the read-after is safe.
    norm = state.sum()  # graftlint: disable=GL009
    return out, norm
