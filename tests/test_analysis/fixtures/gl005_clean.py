"""GL005 clean: rebind the result over the donated name, one suppressed."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def rebind(state, batches):
    for batch in batches:
        state = step(state, batch)
    return state


def copy_kept_alive(state, batch):
    snapshot = state + 0  # materialize a copy before donating
    state = step(state, batch)
    return state, snapshot


def checked_on_cpu_only(state, batch):
    out = step(state, batch)
    return out, state.shape  # graftlint: disable=GL005
