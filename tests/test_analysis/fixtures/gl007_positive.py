"""GL007 true positives: the exact non-atomic checkpoint shapes that lose a
preempted run — delete-then-write and in-place final writes."""

import json
import os
import pickle
import shutil


def save_checkpoint_delete_then_write(ckpt_path, ckptr, arrays, aux):
    # The seed bug: the old snapshot is gone before the new one exists.
    if os.path.exists(ckpt_path):
        shutil.rmtree(ckpt_path)  # <- GL007
    ckptr.save(ckpt_path, arrays)
    with open(os.path.join(ckpt_path, "aux.pkl"), "wb") as fp:  # <- GL007
        pickle.dump(aux, fp)


def overwrite_manifest_in_place(ckpt_path, manifest):
    # Torn-file window: a kill mid-dump leaves invalid JSON at the final path.
    with open(os.path.join(ckpt_path, "manifest.json"), "w") as fp:  # <- GL007
        json.dump(manifest, fp)


def clear_and_redump(run_dir, ckptr, state):
    shutil.rmtree(run_dir)  # <- GL007
    os.makedirs(run_dir)
    ckptr.save(os.path.join(run_dir, "state"), state)
