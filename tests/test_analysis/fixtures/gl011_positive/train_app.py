"""Reads against the sibling configs/ tree; one read drifted.

`stale_knob` in configs/config.yaml has no read at all — the dead-key
direction of GL011 reports it at the YAML line.
"""


def main(cfg):
    tag = cfg.run_name
    steps = cfg.num_steps
    lr = cfg.algo.lr
    mom = cfg.algo.momentum
    decay = cfg.algo.weight_decay  # <- GL011
    every = cfg.checkpoint.every
    keep = cfg.checkpoint.keep_last
    return tag, steps, lr, mom, decay, every, keep
