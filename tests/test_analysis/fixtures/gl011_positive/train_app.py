"""Reads against the sibling configs/ tree; one read drifted.

`stale_knob` in configs/config.yaml has no read at all — the dead-key
direction of GL011 reports it at the YAML line. The telemetry block below
exercises the CHAINED alias model (`tele = cfg.telemetry` then
`perf = tele.get("perf") or {}`): reads through the second-level alias
resolve to exact leaves, so a drifted key inside the nested group flags
even though every read is spelled through `.get(...)` fallbacks — the
pre-chaining model skipped such reads wholesale.
"""


def main(cfg):
    tag = cfg.run_name
    steps = cfg.num_steps
    lr = cfg.algo.lr
    mom = cfg.algo.momentum
    decay = cfg.algo.weight_decay  # <- GL011
    every = cfg.checkpoint.every
    keep = cfg.checkpoint.keep_last
    tele = cfg.telemetry
    perf = tele.get("perf") or {}
    armed = perf.get("enabled")
    window = perf.get("harvest_window", 16)  # <- GL011
    return tag, steps, lr, mom, decay, every, keep, armed, window
