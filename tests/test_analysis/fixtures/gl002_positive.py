"""GL002 true positives: syncs inside jit and per-step syncs in host loops."""

from functools import partial

import jax
import numpy as np


@jax.jit
def item_in_jit(x):
    return x.sum().item()  # <- GL002: sync inside jit


@partial(jax.jit, static_argnames=("n",))
def float_on_traced(x, n):
    return float(x) * n  # <- GL002: float() concretizes traced x


@jax.jit
def asarray_in_jit(x):
    return np.asarray(x)  # <- GL002: host materialization inside jit


def scan_body(carry, x):
    return carry + x.item(), x  # <- GL002: sync inside lax.scan body


def run(xs):
    return jax.lax.scan(scan_body, 0.0, xs)


def train_loop(step_fn, state, batches):
    losses = []
    for batch in batches:
        state, loss = step_fn(state, batch)
        losses.append(jax.device_get(loss))  # <- GL002: per-iteration fetch
    return state, losses


def env_boundary(action):
    return action.squeeze().item()  # <- GL002: host-side scalar fetch
