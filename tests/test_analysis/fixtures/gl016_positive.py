"""GL016 positives: host branches on device-fetched values guarding
collective-performing code (the multi-host deadlock shape)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(None, ("data",))


def all_reduce(state):
    fn = shard_map(lambda x: x, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    return fn(state)


def train_gate(state, loss):
    loss_now = float(jax.device_get(loss))
    if loss_now > 100.0:  # <- GL016
        state = all_reduce(state)
    return state


def eval_gate(state, metric):
    score = metric.item()
    if score < 0.0:  # <- GL016
        state = all_reduce(state)
    return state
