"""GL006 true positives: the async pipeline helper is imported, yet the
interaction loop still fetches in-flight policy outputs synchronously."""

import jax
import numpy as np

from sheeprl_tpu.core.interact import InteractionPipeline  # noqa: F401


def rollout(envs, policy_fn, params, obs, steps):
    for _ in range(steps):
        actions_j = policy_fn(params, obs)
        actions = jax.device_get(actions_j)  # <- GL006  # graftlint: disable=GL002
        obs, reward, term, trunc, info = envs.step(actions)
    return obs


def rollout_asarray(vec_envs, policy_fn, params, obs):
    while True:
        out = policy_fn(params, obs)
        actions = np.asarray(out)  # <- GL006
        obs, reward, term, trunc, info = vec_envs.step(actions)
        if term.all():
            return obs


def rollout_block(envs, step_fn, state, obs, steps):
    for _ in range(steps):
        acts = step_fn(state, obs)
        jax.block_until_ready(acts)  # <- GL006  # graftlint: disable=GL002
        obs, *_ = envs.step(acts)
    return obs
