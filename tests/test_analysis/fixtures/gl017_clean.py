"""GL017 clean: the two sanctioned key-discipline shapes (fold_in the shard
index, or shard a pre-split key batch), plus one suppressed lockstep use."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(None, ("data",))


def sample(key, x):
    shard_key = jax.random.fold_in(key, jax.lax.axis_index("data"))
    return x + jax.random.normal(shard_key, x.shape)


sampler = shard_map(sample, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))


def sample_batch(keys, x):
    return x + jax.random.normal(keys[0], x.shape)


batch_sampler = shard_map(
    sample_batch, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data")
)


def lockstep(key, x):
    # Deliberately identical noise per shard (shared exploration schedule).
    return x + jax.random.normal(key, x.shape)


locked = shard_map(lockstep, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))  # graftlint: disable=GL017
