"""GL001 clean: split/fold_in between consumers, plus one suppressed site."""

import jax


def split_between(key):
    k1, k2 = jax.random.split(key)
    return jax.random.normal(k1, (4,)) + jax.random.uniform(k2, (4,))


def fold_in_loop(key):
    total = 0.0
    for i in range(8):
        total += jax.random.normal(jax.random.fold_in(key, i), ())
    return total


def branches_are_exclusive(key, flag):
    if flag:
        return jax.random.normal(key, (4,))
    return jax.random.uniform(key, (4,))


def deliberate_common_noise(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(key, (4,))  # graftlint: disable=GL001
    return a, b
