"""True positives for GL013: jit-in-a-loop and stale closure capture."""

import jax


def retrace_forever(batches):
    outs = []
    for batch in batches:

        @jax.jit
        def scaled(x):  # <- GL013
            return x * 2

        outs.append(scaled(batch))
    return outs


def stale_capture(params):
    scale = 1.0

    @jax.jit
    def apply(x):  # <- GL013
        return x * scale

    scale = 2.0  # silently ignored by the compiled executable
    return apply(params), scale
