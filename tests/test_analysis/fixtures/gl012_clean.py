"""Clean for GL012: sanctioned hatches in-trace, host effects out of it."""

import time

import jax
import jax.numpy as jnp


def _traced(params, batch):
    # The supported way to do host work under a trace.
    jax.debug.print("loss {l}", l=jnp.mean(batch))
    return params


def _profiled(params):
    # Trace-time stamp is intentional: it marks *compilation*, not steps.
    _ = time.time()  # graftlint: disable=GL012
    return params


@jax.jit
def step(params, batch):
    return _traced(_profiled(params), batch)


def host_loop(params, batches):
    # Callers of a jitted function are host code, not in the closure.
    t0 = time.time()
    for batch in batches:
        params = step(params, batch)
    return params, time.time() - t0
