"""Every axis reference goes through the exported constants; the one
deliberate literal (a spec for an external mesh) is suppressed."""

import jax
from jax.sharding import PartitionSpec as P

try:
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover - pinned-range fallback
    shard_map = None

from gl014_clean.axes import DATA_AXIS, MODEL_AXIS

BATCH_SPEC = P(DATA_AXIS)
WIDE_SPEC = P(DATA_AXIS, MODEL_AXIS)
FOREIGN_SPEC = P("expert")  # graftlint: disable=GL014


def mean_over_replicas(x):
    return jax.lax.pmean(x, DATA_AXIS)


def make_reducer(mesh):
    return shard_map(
        mean_over_replicas, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P()
    )
