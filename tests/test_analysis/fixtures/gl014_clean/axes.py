"""Canonical axis declarations: exported constants plus the one mesh."""

from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def build_mesh(devices):
    return Mesh(devices, (DATA_AXIS, MODEL_AXIS))
