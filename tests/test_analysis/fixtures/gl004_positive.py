"""GL004 true positives: traced branching and unhashable static args."""

from functools import partial

import jax


@jax.jit
def branch_on_traced(x, threshold):
    if threshold > 0:  # <- GL004: python branch on traced value
        return x + 1
    return x - 1


@jax.jit
def loop_on_traced(x, steps):
    while steps > 0:  # <- GL004: python while on traced value
        x = x * 2
        steps = steps - 1
    return x


@partial(jax.jit, static_argnames=("sizes",))
def reshape_to(x, sizes):
    return x.reshape(sizes)


def caller(x):
    return reshape_to(x, sizes=[2, 2])  # <- GL004: unhashable static literal


resize = jax.jit(lambda x, shape: x.reshape(shape), static_argnums=(1,))


def caller_positional(x):
    return resize(x, [4, 1])  # <- GL004: unhashable at static position
