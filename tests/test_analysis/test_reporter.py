"""The JSON and SARIF reports are stable interfaces: CI and tooling parse
them (SARIF specifically feeds GitHub code-scanning annotators)."""

import json

from sheeprl_tpu.analysis import lint_source
from sheeprl_tpu.analysis.reporter import (
    JSON_SCHEMA_VERSION,
    SARIF_SCHEMA,
    SARIF_VERSION,
    render_json,
    render_sarif,
    render_text,
)

_BAD = "from jax import shard_map\n"


def _report(source=_BAD):
    findings, suppressed = lint_source(source, path="sample.py")
    return json.loads(render_json(findings, files_scanned=1, suppressed=suppressed))


def test_json_schema_top_level_keys_and_types():
    payload = _report()
    assert payload["schema_version"] == JSON_SCHEMA_VERSION == 1
    assert payload["tool"] == "graftlint"
    assert isinstance(payload["files_scanned"], int)
    assert isinstance(payload["baselined"], int)
    assert isinstance(payload["suppressed"], int)
    assert isinstance(payload["findings"], list)
    assert isinstance(payload["counts"], dict)
    # The key set itself is part of the contract.
    assert set(payload) == {
        "schema_version",
        "tool",
        "files_scanned",
        "baselined",
        "suppressed",
        "findings",
        "counts",
    }


def test_json_finding_shape():
    finding = _report()["findings"][0]
    assert set(finding) == {"rule", "path", "line", "col", "message", "snippet"}
    assert finding["rule"] == "GL003"
    assert finding["path"] == "sample.py"
    assert finding["line"] == 1
    assert isinstance(finding["col"], int) and finding["col"] >= 1
    assert finding["snippet"] == "from jax import shard_map"


def test_json_counts_aggregate_by_rule():
    payload = _report(_BAD + "from jax import pjit\n")
    assert payload["counts"] == {"GL003": 2}


def test_empty_report_is_clean():
    payload = _report("x = 1\n")
    assert payload["findings"] == []
    assert payload["counts"] == {}


def test_text_report_has_clickable_locations_and_summary():
    findings, _ = lint_source(_BAD, path="sample.py")
    text = render_text(findings, files_scanned=1)
    assert text.splitlines()[0].startswith("sample.py:1:1: GL003 ")
    assert "1 finding(s) in 1 file(s)" in text


def test_syntax_error_becomes_gl000_not_a_crash():
    findings, _ = lint_source("def broken(:\n", path="broken.py")
    assert [f.rule for f in findings] == ["GL000"]


# ------------------------------------------------------------------- SARIF
def _sarif(source=_BAD):
    findings, suppressed = lint_source(source, path="sample.py")
    return json.loads(render_sarif(findings, files_scanned=1, suppressed=suppressed))


def test_sarif_log_shape():
    payload = _sarif()
    assert payload["$schema"] == SARIF_SCHEMA
    assert payload["version"] == SARIF_VERSION == "2.1.0"
    assert len(payload["runs"]) == 1
    run = payload["runs"][0]
    assert run["columnKind"] == "utf16CodeUnits"
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    assert isinstance(driver["version"], str)


def test_sarif_rule_table_is_complete_even_on_clean_scans():
    """A clean run must still document what was checked."""
    from sheeprl_tpu.analysis.registry import all_rules

    run = _sarif("x = 1\n")["runs"][0]
    assert run["results"] == []
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert ids == [r.id for r in all_rules()]
    for rule in run["tool"]["driver"]["rules"]:
        assert rule["fullDescription"]["text"]
        assert rule["defaultConfiguration"]["level"] == "warning"


def test_sarif_rule_table_carries_explain_cards():
    """Code-scanning UIs surface `help.text`; every rule ships its full
    explain card (rationale + hazard shape + suppression recipe) there."""
    run = _sarif("x = 1\n")["runs"][0]
    for rule in run["tool"]["driver"]["rules"]:
        help_text = rule["help"]["text"]
        assert help_text.startswith(rule["id"])
        assert "Hazard shape:" in help_text
        assert f"graftlint: disable={rule['id']}" in help_text


def test_sarif_result_shape_and_rule_index():
    run = _sarif()["runs"][0]
    result = run["results"][0]
    assert result["ruleId"] == "GL003"
    assert result["level"] == "warning"
    assert result["message"]["text"]
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "sample.py"
    region = loc["region"]
    assert region["startLine"] == 1 and region["startColumn"] >= 1
    assert region["snippet"]["text"] == "from jax import shard_map"
    # ruleIndex must point back into the driver's rule table.
    rules = run["tool"]["driver"]["rules"]
    assert rules[result["ruleIndex"]]["id"] == "GL003"


def test_sarif_run_properties_carry_scan_counters():
    props = _sarif()["runs"][0]["properties"]
    assert set(props) == {"filesScanned", "baselined", "suppressed"}
    assert props["filesScanned"] == 1
