"""CLI contract: exit codes, JSON mode, rule selection."""

import json
import os

import pytest

from sheeprl_tpu.analysis.__main__ import main

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_fixture_corpus_exits_nonzero(capsys):
    assert main([FIXTURES, "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "GL003" in out and "finding(s)" in out


def test_clean_file_exits_zero(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("import jax\n\n\ndef f(x):\n    return x\n")
    assert main([str(clean), "--no-baseline"]) == 0


def test_repo_scan_is_clean_without_baseline(capsys):
    """Acceptance: `python -m sheeprl_tpu.analysis sheeprl_tpu/` is clean,
    with no baseline file in play."""
    package_dir = os.path.join(REPO_ROOT, "sheeprl_tpu")
    assert main([package_dir]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out
    assert "baselined" not in out


def test_json_output_parses(capsys):
    positive = os.path.join(FIXTURES, "gl003_positive.py")
    assert main([positive, "--json", "--no-baseline"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["tool"] == "graftlint"
    assert payload["counts"].get("GL003", 0) >= 1


def test_select_restricts_rules(capsys):
    assert main([FIXTURES, "--no-baseline", "--select", "GL005"]) == 1
    payload_rules = {
        line.split()[1]
        for line in capsys.readouterr().out.splitlines()
        if ": GL" in line
    }
    assert payload_rules == {"GL005"}


def test_unknown_rule_and_missing_path_are_usage_errors():
    assert main([FIXTURES, "--select", "GL999"]) == 2
    assert main([os.path.join(FIXTURES, "no_such_file.py")]) == 2


def test_list_rules_names_all_five(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("GL001", "GL002", "GL003", "GL004", "GL005"):
        assert rule_id in out


def test_list_rules_names_the_shardlint_pack(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("GL014", "GL015", "GL016", "GL017", "GL018"):
        assert rule_id in out


@pytest.mark.parametrize(
    "rule_id", [f"GL{n:03d}" for n in range(1, 19)]
)
def test_explain_prints_a_card_for_every_rule(rule_id, capsys):
    """Every registered rule explains itself: id+name header, the rationale,
    a hazard shape, and the suppression recipe."""
    assert main(["--explain", rule_id]) == 0
    out = capsys.readouterr().out
    assert out.startswith(rule_id)
    assert "Hazard shape:" in out, f"{rule_id} has no hazard example"
    assert f"graftlint: disable={rule_id}" in out


def test_explain_is_case_insensitive(capsys):
    assert main(["--explain", "gl015"]) == 0
    assert capsys.readouterr().out.startswith("GL015")


def test_explain_unknown_rule_is_usage_error(capsys):
    assert main(["--explain", "GL999"]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_sarif_format_repo_scan(capsys):
    """Acceptance: `--format sarif` over the package emits parseable
    SARIF 2.1.0 with the graftlint driver."""
    package_dir = os.path.join(REPO_ROOT, "sheeprl_tpu")
    assert main([package_dir, "--format", "sarif"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == "2.1.0"
    assert payload["runs"][0]["tool"]["driver"]["name"] == "graftlint"


def test_json_flag_conflicts_with_other_format():
    assert main([FIXTURES, "--json", "--format", "sarif"]) == 2


def test_changed_only_filters_reported_findings(tmp_path, monkeypatch, capsys):
    """Analysis runs project-wide, but only findings in files changed vs the
    ref are reported."""
    import subprocess

    def git(*argv):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *argv],
            cwd=tmp_path,
            check=True,
            capture_output=True,
        )

    git("init", "-q")
    (tmp_path / "clean.py").write_text("x = 1\n")
    (tmp_path / "bad.py").write_text("from jax import shard_map\n")
    git("add", ".")
    git("commit", "-q", "-m", "seed")
    (tmp_path / "clean.py").write_text("x = 2\n")
    monkeypatch.chdir(tmp_path)
    # bad.py is unchanged vs HEAD, so its GL003 finding is not reported...
    assert main([".", "--no-baseline", "--changed-only", "HEAD"]) == 0
    capsys.readouterr()
    # ...but a full scan still fails on it.
    assert main([".", "--no-baseline"]) == 1


def test_changed_only_unresolvable_ref_reports_everything(capsys):
    fixture = os.path.join(FIXTURES, "gl003_positive.py")
    assert main([fixture, "--no-baseline", "--changed-only", "no-such-ref-xyz"]) == 1
    assert "could not diff" in capsys.readouterr().err


def test_write_baseline_then_clean(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n")
    baseline = tmp_path / "baseline.json"
    assert main([str(bad), "--baseline", str(baseline), "--write-baseline"]) == 0
    assert baseline.is_file()
    # Grandfathered: same scan is now clean...
    assert main([str(bad), "--baseline", str(baseline)]) == 0
    # ...but a NEW violation still fails.
    bad.write_text("from jax import shard_map\nfrom jax import pjit\n")
    assert main([str(bad), "--baseline", str(baseline)]) == 1
